"""Pallas fused kernels vs XLA reference (OpTest contract: numpy/XLA
reference + gradient comparison, SURVEY.md §4 op unit tests).

On CPU the kernels run in pallas interpret mode; the same code compiles via
Mosaic on TPU (validated by bench/driver runs)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.layer_norm import layer_norm


def _attn_ref(q, k, v, causal):
    qh, kh, vh = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(m, s, -1e30)
    w = jax.nn.softmax(s, -1)
    return jnp.swapaxes(jnp.einsum("bhst,bhtd->bhsd", w, vh), 1, 2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_fwd_bwd(causal):
    rs = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rs.randn(2, 128, 2, 64), jnp.float32)
               for _ in range(3)]
    out = flash_attention(q, k, v, causal=causal)
    ref = _attn_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    g1 = jax.grad(lambda *a: (flash_attention(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (_attn_ref(*a, causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_attention_jit_and_bf16():
    rs = np.random.RandomState(1)
    q, k, v = [jnp.asarray(rs.randn(1, 128, 2, 64), jnp.bfloat16)
               for _ in range(3)]
    out = jax.jit(lambda *a: flash_attention(*a, causal=True))(q, k, v)
    ref = _attn_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_flash_attention_fallback_shapes():
    q = jnp.zeros((1, 129, 2, 64))  # 129 % 128 != 0
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)


def test_layer_norm_fwd_bwd():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(8, 16, 256), jnp.float32)
    w = jnp.asarray(rs.randn(256), jnp.float32)
    b = jnp.asarray(rs.randn(256), jnp.float32)

    def ref(x, w, b, eps=1e-5):
        m = jnp.mean(x, -1, keepdims=True)
        v = jnp.mean((x - m) ** 2, -1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + eps) * w + b

    np.testing.assert_allclose(np.asarray(layer_norm(x, w, b)),
                               np.asarray(ref(x, w, b)),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda *a: (layer_norm(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(x, w, b)
    for a, bb in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-3)


def test_fused_op_dispatch_falls_back_cleanly(monkeypatch):
    """ops.fused attempts pallas, hits NotImplementedError on an untileable
    shape, and falls back to the XLA path with a correct result."""
    import paddle_tpu as paddle
    from paddle_tpu.ops import fused

    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    x = paddle.randn([2, 129, 4, 16])  # 129 % 128 != 0 → pallas raises
    out = fused.scaled_dot_product_attention(x, x, x)
    assert out.shape == [2, 129, 4, 16]
    ref = _attn_ref(x.value, x.value, x.value, False)
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


class TestFusedLinearCrossEntropy:
    """Chunked LM-head matmul + xent vs the direct computation."""

    def _direct(self, h, w, labels):
        z = (h.astype(np.float64) @ w.astype(np.float64))
        m = z.max(-1, keepdims=True)
        lse = np.log(np.exp(z - m).sum(-1)) + m[:, 0]
        picked = z[np.arange(len(labels)), labels]
        return lse - picked

    def test_forward_matches_direct(self):
        from paddle_tpu.ops import fused
        rs = np.random.RandomState(0)
        N, H, V = 12, 16, 1000
        h = rs.randn(N, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = rs.randint(0, V, N)
        out = fused.fused_linear_cross_entropy(
            paddle.to_tensor(h), paddle.to_tensor(w),
            paddle.to_tensor(labels), chunk_size=128)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   self._direct(h, w, labels), rtol=1e-4)

    def test_vocab_not_multiple_of_chunk(self):
        from paddle_tpu.ops import fused
        rs = np.random.RandomState(1)
        N, H, V = 6, 8, 37  # 37 not divisible by 16
        h = rs.randn(N, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = rs.randint(0, V, N)
        out = fused.fused_linear_cross_entropy(
            paddle.to_tensor(h), paddle.to_tensor(w),
            paddle.to_tensor(labels), chunk_size=16)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   self._direct(h, w, labels), rtol=1e-4)

    def test_gradients_match_direct(self):
        from paddle_tpu.ops import fused
        import jax
        import jax.numpy as jnp
        rs = np.random.RandomState(2)
        N, H, V = 8, 12, 300
        h = rs.randn(N, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = jnp.asarray(rs.randint(0, V, N))

        def fused_loss(hh, ww):
            return fused._flce(hh, ww, labels, 64).mean()

        def direct_loss(hh, ww):
            z = (hh @ ww).astype(jnp.float32)
            lp = jax.nn.log_softmax(z, -1)
            return -jnp.take_along_axis(lp, labels[:, None], 1).mean()

        gh1, gw1 = jax.grad(fused_loss, (0, 1))(jnp.asarray(h),
                                                jnp.asarray(w))
        gh2, gw2 = jax.grad(direct_loss, (0, 1))(jnp.asarray(h),
                                                 jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2),
                                   rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                                   rtol=1e-3, atol=1e-6)

    def test_batched_leading_shape(self):
        from paddle_tpu.ops import fused
        rs = np.random.RandomState(3)
        B, S, H, V = 2, 5, 8, 50
        h = rs.randn(B, S, H).astype("f")
        w = (rs.randn(H, V) * 0.1).astype("f")
        labels = rs.randint(0, V, (B, S))
        out = fused.fused_linear_cross_entropy(
            paddle.to_tensor(h), paddle.to_tensor(w),
            paddle.to_tensor(labels), chunk_size=16)
        assert tuple(out.shape) == (B, S)
        flat = self._direct(h.reshape(-1, H), w, labels.reshape(-1))
        np.testing.assert_allclose(np.asarray(out.numpy()).reshape(-1),
                                   flat, rtol=1e-4)


# ===========================================================================
# PR 15 kernel suite (tools/kernels_smoke.sh): masked flash + VJP, paged
# decode, softmax-xent, bias-gelu, GSPMD composition, dispatch telemetry
# ===========================================================================
def _attn_ref_masked(q, k, v, causal=False, mask=None):
    qh, kh, vh = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(m, s, -1e30)
    if mask is not None:
        m = mask
        if m.dtype == jnp.bool_:
            s = jnp.where(m, s, -1e30)
        else:
            s = s + m
    w = jax.nn.softmax(s, -1)
    return jnp.swapaxes(jnp.einsum("bhst,bhtd->bhsd", w, vh), 1, 2)


def _qkv(rs, b=2, s=128, h=2, d=64):
    return [jnp.asarray(rs.randn(b, s, h, d), jnp.float32) for _ in range(3)]


@pytest.mark.kernels
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kind", ["bool_pad", "additive", "per_head"])
def test_flash_attention_masked_fwd_bwd(causal, kind):
    """Bool padding masks, additive biases, and per-head biases all run
    through the kernel — forward AND gradient parity vs the XLA softmax."""
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs)
    b, s, h, _ = q.shape
    if kind == "bool_pad":
        # [B, 1, 1, S] key-padding mask (True = attend), MHA's shape
        mask = jnp.asarray(rs.rand(b, 1, 1, s) > 0.2)
        mask = mask.at[:, :, :, :8].set(True)  # no fully-masked rows
    elif kind == "additive":
        mask = jnp.asarray(rs.randn(b, 1, s, s), jnp.float32)
    else:
        mask = jnp.asarray(rs.randn(b, h, s, s), jnp.float32)

    out = flash_attention(q, k, v, causal=causal, mask=mask)
    ref = _attn_ref_masked(q, k, v, causal, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    g1 = jax.grad(
        lambda *a: (flash_attention(*a, causal=causal, mask=mask) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(
        lambda *a: (_attn_ref_masked(*a, causal, mask) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.kernels
def test_flash_attention_mask_shapes_and_fallback():
    rs = np.random.RandomState(4)
    q, k, v = _qkv(rs, b=1, s=128)
    # 2D [S, S] additive mask broadcasts
    m2 = jnp.asarray(rs.randn(128, 128), jnp.float32)
    out = flash_attention(q, k, v, mask=m2)
    ref = _attn_ref_masked(q, k, v, mask=m2[None, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    # non-broadcastable mask raises (dispatch falls back, counted)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, mask=jnp.zeros((3, 1, 128, 128)))


@pytest.mark.kernels
def test_flash_attention_invisible_under_remat():
    """jax.checkpoint over the kernel (cfg.recompute wraps blocks in
    remat): same values, same gradients — the custom VJP must not leak
    residuals the remat pass can't rematerialize."""
    rs = np.random.RandomState(5)
    q, k, v = _qkv(rs)
    mask = jnp.asarray(rs.rand(2, 1, 1, 128) > 0.2)

    def f(q, k, v):
        return (flash_attention(q, k, v, causal=True, mask=mask) ** 2).sum()

    g_plain = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_remat = jax.grad(jax.checkpoint(f), argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g_plain, g_remat):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-6, atol=1e-6)


@pytest.mark.kernels
def test_sharded_flash_attention_tp2_parity():
    """shard_map composition over dp×tp (SpecLayout's axes, 8 virtual
    devices): each shard runs the kernel on its LOCAL heads; results
    match the single-device kernel and the XLA reference."""
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.ops.pallas.flash_attention import sharded_flash_attention

    rs = np.random.RandomState(6)
    q, k, v = _qkv(rs, b=4, s=128, h=2, d=64)
    mask = jnp.asarray(rs.randn(4, 1, 128, 128), jnp.float32)
    mesh = build_mesh({"dp": 4, "tp": 2})
    out = sharded_flash_attention(q, k, v, mesh, head_axis="tp",
                                  batch_axes=("dp",), causal=True, mask=mask)
    ref = _attn_ref_masked(q, k, v, True, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    # heads not divisible by tp -> clean refusal for the dispatch gate
    with pytest.raises(NotImplementedError):
        sharded_flash_attention(q[:, :, :1], k[:, :, :1], v[:, :, :1],
                                mesh, head_axis="tp")


@pytest.mark.kernels
def test_sdpa_dispatch_routes_masked_through_pallas(monkeypatch):
    """fused.scaled_dot_product_attention with a mask no longer falls
    back: pallas result == XLA composite, and the fallback counter stays
    flat."""
    from paddle_tpu.ops import fused

    rs = np.random.RandomState(7)
    q = paddle.to_tensor(rs.randn(2, 128, 4, 16).astype("f"))
    mask = paddle.to_tensor(rs.randn(2, 1, 128, 128).astype("f"))
    ref = fused.scaled_dot_product_attention(q, q, q, attn_mask=mask,
                                             is_causal=True)
    before = dict(fused.fallback_counter().values)
    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    out = fused.scaled_dot_product_attention(q, q, q, attn_mask=mask,
                                             is_causal=True)
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref.value),
                               rtol=1e-4, atol=1e-5)
    assert dict(fused.fallback_counter().values) == before

    # an ambient mesh whose axes do NOT divide this call (dp=8, B=2 —
    # what init_parallel_env leaves behind) must shed the axes and stay
    # on the kernel path, not fall back
    from paddle_tpu.distributed.mesh import build_mesh, mesh_guard

    with mesh_guard(build_mesh({"dp": 8})):
        out_m = fused.scaled_dot_product_attention(q, q, q, attn_mask=mask,
                                                   is_causal=True)
    np.testing.assert_allclose(np.asarray(out_m.value), np.asarray(ref.value),
                               rtol=1e-4, atol=1e-5)
    assert dict(fused.fallback_counter().values) == before


@pytest.mark.kernels
def test_fallback_counter_and_warn_once(monkeypatch):
    """Satellite: the silent-fallback gate warns once per (kernel,
    reason) site and counts every occurrence in the shared registry."""
    import warnings

    from paddle_tpu.ops import fused
    from paddle_tpu.utils.metrics import default_registry

    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    monkeypatch.setattr(fused, "_warned_sites", set())
    counter = fused.fallback_counter()
    key = ("flash_attention", "dropout")
    base = counter.values.get(key, 0)
    x = paddle.randn([1, 16, 2, 8])
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fused.scaled_dot_product_attention(x, x, x, dropout_p=0.5,
                                           training=True)
        fused.scaled_dot_product_attention(x, x, x, dropout_p=0.5,
                                           training=True)
    msgs = [str(r.message) for r in w
            if issubclass(r.category, RuntimeWarning)
            and "flash_attention" in str(r.message)]
    assert len(msgs) == 1, msgs  # warned ONCE
    assert counter.values[key] == base + 2  # counted TWICE
    assert "paddle_pallas_fallbacks_total" in msgs[0]
    # and the shared registry renders it for /metrics
    text = default_registry().prometheus_text()
    assert 'paddle_pallas_fallbacks_total{kernel="flash_attention"' \
           ',reason="dropout"}' in text


@pytest.mark.kernels
def test_paged_decode_attention_ragged_parity():
    """Ragged page-table rows (different lengths, -1 tails, one lane
    exactly at a page boundary, one mid-page) vs the dense-gather
    reference decode_pages used before this kernel."""
    from paddle_tpu.ops.pallas.paged_attention import paged_decode_attention

    rs = np.random.RandomState(8)
    slots, pps, ps, nh, hd = 4, 4, 8, 2, 16
    num_pages = 12
    seq_cap = 32
    q = jnp.asarray(rs.randn(slots, nh, hd), jnp.float32)
    kp = jnp.asarray(rs.randn(num_pages, ps, nh, hd), jnp.float32)
    vp = jnp.asarray(rs.randn(num_pages, ps, nh, hd), jnp.float32)
    rows = jnp.asarray([[2, 5, -1, -1],    # two pages, mid-page pos
                        [7, 1, 3, 9],      # full table
                        [4, -1, -1, -1],   # single page
                        [6, 8, -1, -1]],   # pos exactly at page boundary
                       jnp.int32)
    pos = jnp.asarray([11, 26, 3, 15], jnp.int32)

    def dense_ref():
        gidx = jnp.clip(rows, 0, num_pages - 1)
        kg = kp[gidx].reshape(slots, pps * ps, nh, hd)[:, :seq_cap]
        vg = vp[gidx].reshape(slots, pps * ps, nh, hd)[:, :seq_cap]
        s = jnp.einsum("bnd,bsnd->bns", q, kg) / np.sqrt(hd)
        valid = jnp.arange(seq_cap)[None, :] <= pos[:, None]
        s = jnp.where(valid[:, None, :], s, -1e30)
        w = jax.nn.softmax(s, -1)
        return jnp.einsum("bns,bsnd->bnd", w, vg)

    out = paged_decode_attention(q, kp, vp, rows, pos, seq_cap)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_ref()),
                               rtol=1e-5, atol=1e-5)
    # jit (engine decode executables wrap it) — same result
    out_j = jax.jit(lambda *a: paged_decode_attention(*a, seq_cap))(
        q, kp, vp, rows, pos)
    np.testing.assert_allclose(np.asarray(out_j), np.asarray(out),
                               rtol=0, atol=0)


@pytest.mark.kernels
def test_paged_decode_attention_refusals():
    from paddle_tpu.ops.pallas.paged_attention import paged_decode_attention

    q = jnp.zeros((2, 2, 16))
    kp = jnp.zeros((4, 8, 2, 16))
    rows = jnp.zeros((2, 2), jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    with pytest.raises(NotImplementedError):  # table too narrow
        paged_decode_attention(q, kp, kp, rows, pos, seq_cap=64)
    with pytest.raises(NotImplementedError):  # head mismatch
        paged_decode_attention(q, kp[:, :, :1], kp[:, :, :1], rows, pos, 16)


@pytest.mark.kernels
def test_sharded_paged_decode_tp2_parity():
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_decode_attention, sharded_paged_decode_attention)

    rs = np.random.RandomState(9)
    slots, ps, nh, hd = 2, 8, 4, 16
    q = jnp.asarray(rs.randn(slots, nh, hd), jnp.float32)
    kp = jnp.asarray(rs.randn(6, ps, nh, hd), jnp.float32)
    vp = jnp.asarray(rs.randn(6, ps, nh, hd), jnp.float32)
    rows = jnp.asarray([[1, 3], [5, -1]], jnp.int32)
    pos = jnp.asarray([12, 5], jnp.int32)
    mesh = build_mesh({"dp": 4, "tp": 2})
    out = sharded_paged_decode_attention(q, kp, vp, rows, pos, 16, mesh,
                                         "tp")
    ref = paged_decode_attention(q, kp, vp, rows, pos, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.kernels
def test_decode_pages_kernel_vs_dense_token_path(monkeypatch):
    """GPTAttention.decode_pages with the kernel produces the same
    context (to f32 tolerance) and the SAME page-pool contents as the
    dense-gather path, and the kernel call does not add steady-state
    recompiles (same jitted callable serves different table contents)."""
    from paddle_tpu.models.gpt import GPTAttention, GPTConfig
    from paddle_tpu.ops import fused
    from paddle_tpu.tensor import Tensor, unwrap

    cfg = GPTConfig(hidden_size=32, num_heads=2, num_layers=1,
                    vocab_size=64, dropout=0.0, attn_dropout=0.0)
    attn = GPTAttention(cfg)
    attn.eval()
    rs = np.random.RandomState(10)
    slots, pps, ps, nh, hd = 2, 2, 8, 2, 16
    x = rs.randn(slots, 1, 32).astype("f")
    kp = rs.randn(6, ps, nh, hd).astype("f")
    vp = rs.randn(6, ps, nh, hd).astype("f")
    rows = np.asarray([[1, 4], [2, -1]], np.int32)
    pos = np.asarray([9, 3], np.int32)
    active = np.asarray([True, True])

    def run():
        o, kk, vv = attn.decode_pages(
            Tensor(jnp.asarray(x)), Tensor(jnp.asarray(kp.copy())),
            Tensor(jnp.asarray(vp.copy())), Tensor(jnp.asarray(rows)),
            Tensor(jnp.asarray(pos)), Tensor(jnp.asarray(active)), 16)
        return [np.asarray(unwrap(t)) for t in (o, kk, vv)]

    o_ref, k_ref, v_ref = run()
    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    o_pal, k_pal, v_pal = run()
    np.testing.assert_allclose(o_pal, o_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(k_pal, k_ref)  # scatter untouched
    np.testing.assert_array_equal(v_pal, v_ref)

    # compile tripwire: one jitted decode fn serves changed rows/pos
    calls = jax.jit(lambda r, p: unwrap(attn.decode_pages(
        Tensor(jnp.asarray(x)), Tensor(jnp.asarray(kp)),
        Tensor(jnp.asarray(vp)), Tensor(r), Tensor(p),
        Tensor(jnp.asarray(active)), 16)[0]))
    calls(jnp.asarray(rows), jnp.asarray(pos))
    calls(jnp.asarray([[0, 5], [3, -1]], jnp.int32),
          jnp.asarray([14, 7], jnp.int32))
    assert calls._cache_size() == 1


@pytest.mark.kernels
def test_softmax_xent_fwd_bwd_parity():
    """Fused loss kernel vs the XLA composite: unpadded AND padded
    (vocab % 128 != 0, rows % 8 != 0), ignore_index rows, gradients."""
    from paddle_tpu.ops.pallas.softmax_xent import softmax_xent

    rs = np.random.RandomState(11)
    for (n, v) in [(32, 512), (37, 1000)]:
        z = jnp.asarray(rs.randn(n, v), jnp.float32)
        lab = jnp.asarray(rs.randint(0, v, n), jnp.int32)
        lab = lab.at[0].set(-100)

        def ref(z, lab):
            lp = jax.nn.log_softmax(z, -1)
            pick = jnp.take_along_axis(lp, lab[:, None].clip(0), 1)[:, 0]
            return jnp.where(lab == -100, 0.0, -pick)

        out = softmax_xent(z, lab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref(z, lab)),
                                   rtol=1e-5, atol=1e-5)
        g1 = jax.grad(lambda zz: softmax_xent(zz, lab).sum())(z)
        g2 = jax.grad(lambda zz: ref(zz, lab).sum())(z)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)
        # ignore rows get exactly zero gradient
        assert float(jnp.abs(g1[0]).max()) == 0.0


@pytest.mark.kernels
def test_cross_entropy_gate_reaches_kernel(monkeypatch):
    """nn.functional.cross_entropy -> ops/fused gate -> pallas kernel:
    same loss as the flag-off composite, batched [B, S, V] logits."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops import fused

    rs = np.random.RandomState(12)
    logits = paddle.to_tensor(rs.randn(2, 16, 1000).astype("f"))
    labels = paddle.to_tensor(rs.randint(0, 1000, (2, 16)))
    ref = F.cross_entropy(logits, labels, reduction="none")
    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    out = F.cross_entropy(logits, labels, reduction="none")
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref.value),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.kernels
def test_bias_gelu_fwd_bwd_parity():
    from paddle_tpu.ops.pallas.bias_gelu import bias_gelu

    rs = np.random.RandomState(13)
    x = jnp.asarray(rs.randn(16, 8, 256), jnp.float32)
    b = jnp.asarray(rs.randn(256), jnp.float32)

    def ref(x, b):
        return jax.nn.gelu(x + b, approximate=False)

    np.testing.assert_allclose(np.asarray(bias_gelu(x, b)),
                               np.asarray(ref(x, b)),
                               rtol=1e-5, atol=1e-6)
    g1 = jax.grad(lambda *a: (bias_gelu(*a) ** 2).sum(), (0, 1))(x, b)
    g2 = jax.grad(lambda *a: (ref(*a) ** 2).sum(), (0, 1))(x, b)
    for a, bb in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-4)
    with pytest.raises(NotImplementedError):  # rows % 8 != 0 -> dispatch
        bias_gelu(jnp.zeros((7, 256)), jnp.zeros((256,)))


@pytest.mark.kernels
def test_gpt_mlp_and_encoder_ffn_route_fused(monkeypatch):
    """GPTMLP and TransformerEncoderLayer hit fused.linear_bias_gelu with
    no model changes: flag-on output == flag-off output."""
    from paddle_tpu.models.gpt import GPTConfig, GPTMLP
    from paddle_tpu.nn.layer.transformer import TransformerEncoderLayer
    from paddle_tpu.ops import fused

    rs = np.random.RandomState(14)
    mlp = GPTMLP(GPTConfig(hidden_size=64, dropout=0.0))
    mlp.eval()
    x = paddle.to_tensor(rs.randn(2, 8, 64).astype("f"))
    ref = mlp(x)
    enc = TransformerEncoderLayer(64, 4, 128, dropout=0.0,
                                  activation="gelu", attn_dropout=0.0,
                                  act_dropout=0.0)
    enc.eval()
    src = paddle.to_tensor(rs.randn(2, 16, 64).astype("f"))
    enc_ref = enc(src)
    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    np.testing.assert_allclose(np.asarray(mlp(x).value),
                               np.asarray(ref.value),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(enc(src).value),
                               np.asarray(enc_ref.value),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.kernels
def test_masked_training_step_through_kernels(monkeypatch):
    """End-to-end flag-on masked+causal training step: grads flow through
    the flash kernel, the xent kernel, and bias-gelu with ZERO fallbacks
    recorded — the op_report/fallback contract of tools/kernels_smoke.sh
    at unit scale."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops import fused
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rs = np.random.RandomState(15)
    B, S, H, D, V = 2, 128, 2, 64, 512
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    w_out = jnp.asarray(rs.randn(H * D, V) * 0.05, jnp.float32)
    bias = jnp.asarray(rs.randn(V) * 0.05, jnp.float32)
    mask = jnp.asarray(rs.rand(B, 1, 1, S) > 0.1)
    labels = jnp.asarray(rs.randint(0, V, (B, S)), jnp.int32)
    monkeypatch.setattr(fused, "_use_pallas", lambda: True)
    before = dict(fused.fallback_counter().values)

    from paddle_tpu.ops.pallas.bias_gelu import bias_gelu as bg
    from paddle_tpu.ops.pallas.softmax_xent import softmax_xent

    def loss_fn(q, w, b):
        ctx = flash_attention(q, q, q, causal=True, mask=mask)
        h = bg(ctx.reshape(B * S, H * D) @ w, b)
        return softmax_xent(h.reshape(B, S, V), labels).mean()

    loss, grads = jax.value_and_grad(loss_fn, (0, 1, 2))(q, w_out, bias)
    assert np.isfinite(float(loss))
    assert all(bool(jnp.isfinite(g).all()) for g in grads)
    assert dict(fused.fallback_counter().values) == before
