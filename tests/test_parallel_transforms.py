"""Pipeline / sharding / recompute / gradient-merge transform tests.

Mirrors the reference's meta-optimizer test style (SURVEY.md §4: compile a
strategy, assert semantics) on the virtual 8-device CPU mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.pipeline import (
    pipeline_step_fn, stack_stage_params, unstack_stage_params)
from paddle_tpu.distributed.sharding import zero_shardings, shard_spec
from paddle_tpu.distributed.recompute import recompute, checkpoint, \
    recompute_sequential
from paddle_tpu.distributed.grad_merge import gradient_merge


def _stage_params(rs, n_stages, d):
    return [{"w": jnp.asarray(rs.randn(d, d) * 0.1, jnp.float32),
             "b": jnp.asarray(rs.randn(d) * 0.1, jnp.float32)}
            for _ in range(n_stages)]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


class TestPipeline:
    def test_forward_matches_sequential(self):
        S, M, mb, d = 4, 8, 2, 16
        mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
        rs = np.random.RandomState(0)
        per_stage = _stage_params(rs, S, d)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rs.randn(M, mb, d), jnp.float32)

        run = jax.jit(pipeline_step_fn(_stage_fn, mesh))
        out = run(stacked, x)

        ref = x
        for p in per_stage:
            ref = jax.vmap(lambda xx, p=p: _stage_fn(p, xx))(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_sequential(self):
        S, M, mb, d = 4, 4, 2, 8
        mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
        rs = np.random.RandomState(1)
        per_stage = _stage_params(rs, S, d)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rs.randn(M, mb, d), jnp.float32)

        pipe = pipeline_step_fn(_stage_fn, mesh)

        def loss_pipe(params, x):
            return jnp.mean(pipe(params, x) ** 2)

        def loss_ref(stacked, x):
            per = [jax.tree.map(lambda l, i=i: l[i], stacked)
                   for i in range(S)]
            y = x
            for p in per:
                y = jax.vmap(lambda xx, p=p: _stage_fn(p, xx))(y)
            return jnp.mean(y ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked, x)
        g_ref = jax.jit(jax.grad(loss_ref))(stacked, x)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_unstack_roundtrip(self):
        rs = np.random.RandomState(2)
        per = _stage_params(rs, 3, 4)
        back = unstack_stage_params(stack_stage_params(per), 3)
        for a, b in zip(per, back):
            np.testing.assert_allclose(a["w"], b["w"])

    def test_shape_change_rejected(self):
        mesh = build_mesh({"pp": 2}, devices=jax.devices()[:2])
        stacked = {"w": jnp.zeros((2, 4, 8))}
        x = jnp.zeros((2, 2, 4))
        run = pipeline_step_fn(lambda p, a: a @ p["w"], mesh)
        with pytest.raises(Exception):
            jax.jit(run)(stacked, x)


class TestInterleavedPipeline:
    """1F1B interleaved virtual-stage schedule (round-3 next-step #9)."""

    def _run_interleaved(self, stacked_g, x, mesh, S):
        from jax import shard_map
        from paddle_tpu.distributed.pipeline import (
            interleave_chunk_view, spmd_pipeline_interleaved)

        chunked = interleave_chunk_view(stacked_g, S)  # [v, S, ...] view

        def inner(p, mb):
            p = jax.tree.map(lambda l: jnp.squeeze(l, 1), p)
            return spmd_pipeline_interleaved(_stage_fn, p, mb,
                                             axis_name="pp")

        return shard_map(inner, mesh=mesh, in_specs=(P(None, "pp"), P()),
                         out_specs=P(), check_vma=False)(chunked, x)

    def test_forward_matches_sequential_v2(self):
        # 8 blocks on pp=4 -> v=2 chunks per device
        S, L, M, mb, d = 4, 8, 8, 2, 16
        mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
        rs = np.random.RandomState(0)
        per_block = _stage_params(rs, L, d)
        stacked = stack_stage_params(per_block)
        x = jnp.asarray(rs.randn(M, mb, d), jnp.float32)

        out = jax.jit(lambda p, x: self._run_interleaved(p, x, mesh, S))(
            stacked, x)
        ref = x
        for p in per_block:
            ref = jax.vmap(lambda xx, p=p: _stage_fn(p, xx))(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_sequential(self):
        S, L, M, mb, d = 2, 4, 4, 2, 8
        mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
        rs = np.random.RandomState(1)
        per_block = _stage_params(rs, L, d)
        stacked = stack_stage_params(per_block)
        x = jnp.asarray(rs.randn(M, mb, d), jnp.float32)

        def loss_int(params, x):
            return jnp.mean(
                self._run_interleaved(params, x, mesh, S) ** 2)

        def loss_ref(stacked, x):
            y = x
            for i in range(L):
                p = jax.tree.map(lambda l, i=i: l[i], stacked)
                y = jax.vmap(lambda xx, p=p: _stage_fn(p, xx))(y)
            return jnp.mean(y ** 2)

        g_int = jax.jit(jax.grad(loss_int))(stacked, x)
        g_ref = jax.jit(jax.grad(loss_ref))(stacked, x)
        for a, b in zip(jax.tree.leaves(g_int), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_bubble_fraction_below_gpipe(self):
        from paddle_tpu.distributed.pipeline import pipeline_schedule_ticks

        S, M, v = 4, 8, 2
        tg, cg, bg = pipeline_schedule_ticks("F-then-B", S, M, v)
        ti, ci, bi = pipeline_schedule_ticks("1F1B", S, M, v)
        assert (tg, cg) == (M + S - 1, v)
        assert (ti, ci) == (v * M + S - 1, 1)
        # total chunk-work: 22 vs 19 -> bubble 27.3% vs 15.8%
        assert ti * ci < tg * cg
        assert bi < bg
        assert abs(bg - 3 / 11) < 1e-9 and abs(bi - 3 / 19) < 1e-9

    def test_hlo_has_collective_permute_and_ring_wrap(self):
        S, L, M, mb, d = 4, 8, 8, 2, 8
        mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
        rs = np.random.RandomState(2)
        stacked = stack_stage_params(_stage_params(rs, L, d))
        x = jnp.asarray(rs.randn(M, mb, d), jnp.float32)
        hlo = jax.jit(
            lambda p, x: self._run_interleaved(p, x, mesh, S)
        ).lower(stacked, x).compile().as_text()
        assert "collective-permute" in hlo

    def test_unknown_schedule_mode_raises(self):
        from paddle_tpu.distributed.pipeline import (
            PipelineProgram, pipeline_loss_fn, pipeline_schedule_ticks)

        mesh = build_mesh({"pp": 2}, devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="schedule"):
            pipeline_loss_fn(PipelineProgram(), mesh, 2, schedule="1f1b")
        with pytest.raises(ValueError, match="schedule"):
            pipeline_schedule_ticks("Interleaved-v2", 2, 4)

    def test_microbatch_divisibility_enforced(self):
        S = 2
        mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
        rs = np.random.RandomState(3)
        stacked = stack_stage_params(_stage_params(rs, 2, 4))
        x = jnp.zeros((3, 2, 4), jnp.float32)  # M=3 not divisible by 2
        with pytest.raises(Exception, match="divisible"):
            jax.jit(lambda p, x: self._run_interleaved(p, x, mesh, S))(
                stacked, x)


class TestZeroShardings:
    def test_shard_spec_picks_divisible_dim(self):
        assert shard_spec((3, 16), "dp", 8) == P(None, "dp")
        assert shard_spec((5, 3), "dp", 8) == P()
        # largest divisible dim wins (a [vocab, hidden] embedding shards
        # vocab; leaves TP'd dims free for merge_zero_spec)
        assert shard_spec((8, 16), "dp", 8) == P(None, "dp")
        assert shard_spec((32, 16), "dp", 8) == P("dp", None)

    def test_merge_zero_spec_composes_with_tp(self):
        from paddle_tpu.distributed.sharding import merge_zero_spec
        # TP holds dim 0 ('mp'); ZeRO goes to the largest free dim
        assert merge_zero_spec(P("mp", None), (1024, 64), "dp", 8) == \
            P("mp", "dp")
        # already dp-sharded spec untouched
        assert merge_zero_spec(P("dp", None), (64, 64), "dp", 8) == \
            P("dp", None)
        # nothing free & divisible -> TP placement kept, no dp added
        assert merge_zero_spec(P("mp"), (128,), "dp", 8) == P("mp")
        # no TP spec -> plain zero sharding of the largest dim
        assert merge_zero_spec(None, (16, 256), "dp", 8) == P(None, "dp")

    def test_stages(self):
        mesh = build_mesh({"dp": 8})
        params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((3,))}
        opt = {"w": {"m": jnp.zeros((16, 4)), "v": jnp.zeros((16, 4))},
               "b": {"m": jnp.zeros((3,)), "v": jnp.zeros((3,))}}
        p1, o1, g1 = zero_shardings(params, opt, mesh, stage=1)
        assert p1["w"].spec == P() and g1["w"].spec == P()
        assert o1["w"]["m"].spec == P("dp", None)
        assert o1["b"]["m"].spec == P()  # too small to shard -> replicated
        p2, o2, g2 = zero_shardings(params, opt, mesh, stage=2)
        assert g2["w"].spec == P("dp", None) and p2["w"].spec == P()
        p3, _, _ = zero_shardings(params, opt, mesh, stage=3)
        assert p3["w"].spec == P("dp", None)

    def test_zero1_train_step_runs(self):
        mesh = build_mesh({"dp": 8})
        rs = np.random.RandomState(0)
        params = {"w": jnp.asarray(rs.randn(16, 16), jnp.float32)}
        opt = paddle.optimizer.Adam(learning_rate=1e-3)
        state = opt.init_pytree(params)
        p_sh, s_sh, _ = zero_shardings(params, state, mesh, stage=1)
        d_sh = NamedSharding(mesh, P("dp"))

        def step(params, state, x):
            def loss_fn(p):
                return jnp.mean((x @ p["w"]) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(params)
            new_p, new_s = opt.apply_pytree(params, g, state, lr=1e-3, step=1)
            return new_p, new_s, loss

        stepc = jax.jit(step, in_shardings=(p_sh, s_sh, d_sh),
                        out_shardings=(p_sh, s_sh, NamedSharding(mesh, P())))
        x = jax.device_put(jnp.asarray(rs.randn(16, 16), jnp.float32), d_sh)
        params = jax.device_put(params, p_sh)
        state = jax.device_put(state, s_sh)
        new_p, new_s, loss = stepc(params, state, x)
        assert np.isfinite(float(loss))
        # optimizer state really lives sharded over dp
        assert new_s["w"]["moment1"].sharding.spec == P("dp", None) or \
            list(new_s["w"].values())[0].sharding.spec == P("dp", None)


class TestRecompute:
    def test_recompute_value_and_grad(self):
        x = jnp.arange(8.0)

        def f(x):
            return jnp.sum(jnp.sin(x) ** 2)

        assert np.allclose(recompute(f, x), f(x))
        g1 = jax.grad(lambda x: recompute(f, x))(x)
        g2 = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-6)

    def test_policy_names(self):
        f = checkpoint(lambda x: jnp.sum(x * x), policy="dots_saveable")
        assert np.allclose(jax.grad(f)(jnp.ones(3)), 2.0)

    def test_recompute_sequential(self):
        fns = [lambda x: x * 2, lambda x: x + 1, lambda x: x ** 2]
        out = recompute_sequential({"segments": 2}, fns, jnp.asarray(3.0))
        assert np.allclose(out, (3 * 2 + 1) ** 2)


class TestGradientMerge:
    def test_matches_full_batch(self):
        rs = np.random.RandomState(0)
        params = {"w": jnp.asarray(rs.randn(4, 4), jnp.float32)}
        batch = {"x": jnp.asarray(rs.randn(8, 4), jnp.float32),
                 "y": jnp.asarray(rs.randn(8, 4), jnp.float32)}

        def vag(p, b):
            def loss(p):
                return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

            return jax.value_and_grad(loss)(p)

        loss_full, g_full = vag(params, batch)
        merged = gradient_merge(vag, k_steps=4, avg=True)
        loss_m, g_m = jax.jit(merged)(params, batch)
        np.testing.assert_allclose(float(loss_m), float(loss_full), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g_m["w"]),
                                   np.asarray(g_full["w"]), rtol=1e-5)

    def test_sum_mode(self):
        params = {"w": jnp.ones((2, 2))}
        batch = {"x": jnp.ones((4, 2)), "y": jnp.zeros((4, 2))}

        def vag(p, b):
            def loss(p):
                return jnp.sum((b["x"] @ p["w"] - b["y"]) ** 2)

            return jax.value_and_grad(loss)(p)

        merged_avg = gradient_merge(vag, 2, avg=True)
        merged_sum = gradient_merge(vag, 2, avg=False)
        _, ga = merged_avg(params, batch)
        _, gs = merged_sum(params, batch)
        np.testing.assert_allclose(np.asarray(gs["w"]),
                                   2 * np.asarray(ga["w"]), rtol=1e-6)
