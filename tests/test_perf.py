"""Performance introspection suite (`perf` marker — ISSUE 13):

  * monitor/perf.py HLO parser vs XLA's own cost analysis (summed table
    flops within 5% — in practice exact — on a compiled grad step);
  * op-table schema, bound classification, trace-time join, tail rollup
    (sums stay exact);
  * engine.op_report() end-to-end on a CPU train step;
  * buffer census bucket math with known owner-tagged arrays;
  * fake RESOURCE_EXHAUSTED → flight-recorder "oom" dump carrying the
    census;
  * tools/perf_gate.py pass / regression / missing-metric / ratchet;
  * GET /debug/perf JSON + ?format=chrome (span AND device-op tracks).
"""
import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi.model import Model
from paddle_tpu.monitor import flightrec, perf

pytestmark = pytest.mark.perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(d=8, h=16):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(d, h), nn.Tanh(), nn.Linear(h, 1))
    m = Model(net)
    m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters()),
              nn.MSELoss())
    return m


def _engine(m):
    from paddle_tpu.hapi.engine import TrainEngine
    return TrainEngine(m).begin()


def _batch(n=8, d=8):
    x = paddle.to_tensor(np.zeros((n, d), "float32"))
    y = paddle.to_tensor(np.zeros((n, 1), "float32"))
    return [x], [y]


@pytest.fixture(autouse=True)
def _perf_isolation():
    perf.reset()
    yield
    perf.reset()


# -- HLO parser vs XLA cost analysis ----------------------------------------
class TestOpTable:
    def _compiled(self):
        import jax
        import jax.numpy as jnp

        def loss(w1, w2, x):
            return jnp.mean(jnp.tanh(x @ w1) @ w2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1)))
        args = (jnp.zeros((16, 32)), jnp.zeros((32, 4)),
                jnp.zeros((8, 16)))
        return g.lower(*args).compile()

    def test_summed_flops_match_xla_within_5pct(self):
        c = self._compiled()
        ca = c.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        tbl = perf.op_table(c.as_text())
        want = float(ca["flops"])
        got = float(tbl["totals"]["flops"])
        assert want > 0
        assert abs(got - want) <= 0.05 * want, (got, want)
        # the tanh contributes transcendentals, tracked separately
        assert tbl["totals"]["transcendentals"] > 0
        if ca.get("transcendentals"):
            assert tbl["totals"]["transcendentals"] == \
                int(ca["transcendentals"])

    def test_row_schema_and_classification(self):
        tbl = perf.op_table(self._compiled().as_text())
        assert tbl["ops"], "empty op table"
        keys = {"name", "op", "source", "flops", "transcendentals",
                "bytes", "intensity", "bound", "est_ms", "time_ms",
                "time_source", "roofline_frac"}
        for r in tbl["ops"]:
            assert keys <= set(r), r
            assert r["bound"] in ("compute", "memory", "collective",
                                  "mixed")
        assert any(r["op"] in ("dot", "fusion") for r in tbl["ops"])
        # rows are sorted hottest-first
        times = [r["time_ms"] for r in tbl["ops"]]
        assert times == sorted(times, reverse=True)
        assert tbl["ridge_intensity"] > 0

    def test_trace_join_and_attribution(self):
        c = self._compiled()
        base = perf.op_table(c.as_text())
        hot = base["ops"][0]["name"]
        tbl = perf.op_table(
            c.as_text(), measured_step_ms=10.0,
            trace_times={hot: {"total_us": 2000.0, "count": 2}})
        rows = {r["name"]: r for r in tbl["ops"]}
        assert rows[hot]["time_source"] == "trace"
        assert rows[hot]["time_ms"] == pytest.approx(1.0)
        others = [r for r in tbl["ops"] if r["name"] != hot]
        assert all(r["time_source"] == "attributed" for r in others)
        # attributed residual: traced 1ms + spread 9ms == measured wall
        assert sum(r["time_ms"] for r in tbl["ops"]) == \
            pytest.approx(10.0, rel=1e-3)

    def test_tail_rollup_preserves_sums(self):
        text = self._compiled().as_text()
        full = perf.op_table(text)
        rolled = perf.op_table(text, top=2)
        assert len(rolled["ops"]) <= 3
        assert rolled["ops"][-1]["name"] == "(other)"
        assert sum(r["flops"] for r in rolled["ops"]) == \
            full["totals"]["flops"]
        assert rolled["totals"] == full["totals"]


# -- engine.op_report() -----------------------------------------------------
class TestEngineOpReport:
    def test_non_empty_and_flops_match_cost_analysis(self):
        eng = _engine(_model())
        xs, ys = _batch()
        report = eng.op_report(xs, ys)
        assert report["name"] == "train"
        assert report["ops"]
        ca = eng.step_cost_analysis(xs, ys)
        want = float(ca["flops"])
        got = float(report["totals"]["flops"])
        assert abs(got - want) <= 0.05 * want, (got, want)

    def test_cached_batch_allows_argless_call(self):
        eng = _engine(_model())
        xs, ys = _batch()
        eng.step_cost_analysis(xs, ys)   # stashes the example batch
        report = eng.op_report()
        assert report["ops"]

    def test_argless_without_prior_batch_raises(self):
        eng = _engine(_model())
        with pytest.raises(ValueError, match="op_report"):
            eng.op_report()


# -- buffer census ----------------------------------------------------------
class TestBufferCensus:
    def test_bucket_math_with_known_owners(self):
        import jax.numpy as jnp

        a = jnp.zeros((128, 128), jnp.float32)
        b = jnp.zeros((128, 128), jnp.float32)
        c = jnp.zeros((64,), jnp.int32)
        census = perf.buffer_census(owners={"params": [a, b],
                                            "kv_pages": [c]})
        assert census["by_tag"]["params"] == a.nbytes + b.nbytes
        assert census["by_tag"]["kv_pages"] == c.nbytes
        bucket = next(bk for bk in census["buckets"]
                      if bk["tag"] == "params"
                      and bk["shape"] == [128, 128])
        assert bucket["count"] == 2
        assert bucket["bytes"] == 2 * 128 * 128 * 4
        assert census["total_bytes"] == sum(census["by_tag"].values())
        assert census["n_arrays"] >= 3

    def test_unclaimed_arrays_are_activations(self):
        import jax.numpy as jnp

        stray = jnp.ones((33, 7), jnp.float32)
        census = perf.buffer_census(owners={})
        acts = [bk for bk in census["buckets"]
                if bk["tag"] == "activations" and bk["shape"] == [33, 7]]
        assert acts and acts[0]["bytes"] >= stray.nbytes

    def test_registered_suppliers_and_reset(self):
        import jax.numpy as jnp

        w = jnp.zeros((16, 16), jnp.float32)
        perf.register_owner("opt_state", lambda: {"m": w})
        census = perf.buffer_census()
        assert census["by_tag"].get("opt_state", 0) >= w.nbytes
        perf.reset()
        census2 = perf.buffer_census()
        assert "opt_state" not in census2["by_tag"]


# -- OOM postmortem ---------------------------------------------------------
class TestOOMPostmortem:
    def test_is_oom_marker_matching(self):
        assert perf.is_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1234 bytes"))
        assert perf.is_oom(RuntimeError("Resource exhausted: hbm"))
        assert not perf.is_oom(ValueError("shape mismatch"))
        assert not perf.is_oom(None)

    def test_fake_oom_dump_contains_census(self, tmp_path):
        flightrec.reset()
        flightrec.configure(str(tmp_path))
        try:
            import jax.numpy as jnp

            w = jnp.zeros((32, 32), jnp.float32)
            perf.register_owner("params", lambda: [w])
            perf.register_provider("train",
                                   lambda: {"ops": [], "totals": {}})
            exc = RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                               "allocating 9999999 bytes")
            path = perf.oom_postmortem(exc)
            assert path and os.path.exists(path)
            doc = json.load(open(path))
            assert doc["reason"] == "oom"
            census = doc["perf"]["census"]
            assert census["by_tag"]["params"] >= w.nbytes
            assert "train" in doc["perf"]["op_reports"]
            assert "RESOURCE_EXHAUSTED" in doc["perf"]["error"]
            # ring also carries the oom record
            assert any(r["kind"] == "oom" for r in doc["records"])
        finally:
            flightrec.reset()

    def test_enricher_upgrades_crash_to_oom(self, tmp_path):
        flightrec.reset()
        flightrec.configure(str(tmp_path))
        try:
            perf.install_oom_hook()
            out = perf._oom_enricher(
                RuntimeError,
                RuntimeError("RESOURCE_EXHAUSTED: oom"))
            assert out["reason"] == "oom"
            assert "census" in out["extra"]["perf"]
            assert perf._oom_enricher(ValueError,
                                      ValueError("not oom")) is None
        finally:
            flightrec.reset()


# -- perf-regression gate ---------------------------------------------------
class TestPerfGate:
    def _gate(self, tmp_path, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
             "--baseline", str(tmp_path / "baseline.json"), *args],
            capture_output=True, text=True)

    def _write_run(self, tmp_path, name, **over):
        line = {"metric": "bert", "value": 10.0, "unit": "seq/s",
                "vs_baseline": 1.0, "schema_version": 1, "mfu": 0.12,
                "step_time_p50_ms": 50.0, "step_time_p99_ms": 80.0,
                "device_mem_peak_mb": 0.0, "compile_seconds": 3.0,
                "platform": "cpu"}
        line.update(over)
        p = tmp_path / name
        p.write_text(json.dumps(line) + "\n" + json.dumps(
            {"metric": "bench_summary", "value": 0.0,
             "unit": "tpu_configs", "vs_baseline": 0.0}) + "\n")
        return str(p)

    def test_pass_fail_missing_and_ratchet(self, tmp_path):
        run = self._write_run(tmp_path, "run.jsonl")
        # no baseline yet: pass (bootstrap)
        assert self._gate(tmp_path, "--run", run).returncode == 0
        assert self._gate(tmp_path, "--run", run,
                          "--write-baseline").returncode == 0
        # clean re-run passes
        assert self._gate(tmp_path, "--run", run).returncode == 0
        # p50 degraded beyond its 60% CPU band fails
        bad = self._write_run(tmp_path, "bad.jsonl",
                              step_time_p50_ms=90.0)
        r = self._gate(tmp_path, "--run", bad)
        assert r.returncode == 1
        assert "step_time_p50_ms" in r.stdout
        # min-of-N: one good run alongside rescues the noisy one
        good = self._write_run(tmp_path, "good.jsonl",
                               step_time_p50_ms=48.0)
        assert self._gate(tmp_path, "--run", bad,
                          "--run", good).returncode == 0
        # a baseline-known metric gone null fails
        nul = self._write_run(tmp_path, "nul.jsonl", mfu=None)
        r = self._gate(tmp_path, "--run", nul)
        assert r.returncode == 1 and "missing" in r.stdout
        # ratchet: re-baselining from a worse run keeps the better value
        worse = self._write_run(tmp_path, "worse.jsonl", mfu=0.05)
        assert self._gate(tmp_path, "--run", worse,
                          "--write-baseline").returncode == 0
        doc = json.loads((tmp_path / "baseline.json").read_text())
        assert doc["configs"]["bert"]["mfu"]["value"] == \
            pytest.approx(0.12)
        # --force accepts the regression
        assert self._gate(tmp_path, "--run", worse, "--write-baseline",
                          "--force").returncode == 0
        doc = json.loads((tmp_path / "baseline.json").read_text())
        assert doc["configs"]["bert"]["mfu"]["value"] == \
            pytest.approx(0.05)

    def test_errored_config_fails_gate(self, tmp_path):
        run = self._write_run(tmp_path, "run.jsonl")
        assert self._gate(tmp_path, "--run", run,
                          "--write-baseline").returncode == 0
        err = self._write_run(
            tmp_path, "err.jsonl", unit="error", value=0.0, mfu=None,
            step_time_p50_ms=None, step_time_p99_ms=None,
            device_mem_peak_mb=None, compile_seconds=None,
            error="boom")
        r = self._gate(tmp_path, "--run", err)
        assert r.returncode == 1

    def test_bench_lines_carry_gate_schema(self):
        """The contract perf_gate relies on: _gate_normalize puts every
        GATE_METRICS key (null if unmeasured) + schema_version on any
        line, error lines included."""
        sys.path.insert(0, REPO)
        try:
            from bench import (BENCH_SCHEMA_VERSION, GATE_METRICS,
                               _gate_normalize)
        finally:
            sys.path.remove(REPO)
        line = _gate_normalize({"metric": "bert", "value": 0.0,
                                "unit": "error", "error": "boom"})
        assert line["schema_version"] == BENCH_SCHEMA_VERSION
        for key, spec in GATE_METRICS.items():
            assert key in line
            assert spec["direction"] in ("higher", "lower")
            assert spec["cpu_rel_tol"] >= spec["tpu_rel_tol"]


# -- /debug/perf endpoint ---------------------------------------------------
class TestDebugPerfEndpoint:
    def _fetch(self, url):
        return json.loads(
            urllib.request.urlopen(url, timeout=5).read().decode())

    def test_json_and_chrome_roundtrip(self):
        from paddle_tpu.monitor import MonitorServer
        from paddle_tpu.monitor.tracing import Tracer

        eng = _engine(_model())
        xs, ys = _batch()
        perf.register_provider("train",
                               lambda: eng.op_report(xs, ys))
        tracer = Tracer(sample_rate=1.0)
        with tracer.start_span("request"):
            pass
        srv = MonitorServer(port=0, tracer=tracer).start()
        try:
            doc = self._fetch(srv.url + "/debug/perf")
            assert doc["providers"] == ["train"]
            assert doc["reports"]["train"]["ops"]
            assert "census" in doc and "hbm" in doc
            chrome = self._fetch(srv.url + "/debug/perf?format=chrome")
            evs = chrome["traceEvents"]
            # span track (tracer pid) AND device-op track (synthetic pid)
            dev = [e for e in evs if e.get("pid") == 999999
                   and e.get("ph") == "X"]
            spans = [e for e in evs if e.get("pid") != 999999
                     and e.get("ph") == "X"]
            assert dev and spans
            assert any(e["name"] == "request" for e in spans)
            names = [e["name"] for e in evs if e.get("ph") == "M"]
            assert "process_name" in names and "thread_name" in names
            for e in dev:
                assert e["dur"] > 0 and "bound" in e["args"]
        finally:
            srv.shutdown()

    def test_provider_error_does_not_poison_endpoint(self):
        from paddle_tpu.monitor import MonitorServer

        def broken():
            raise RuntimeError("engine gone")

        perf.register_provider("train", broken)
        srv = MonitorServer(port=0).start()
        try:
            doc = self._fetch(srv.url + "/debug/perf")
            assert "RuntimeError" in doc["reports"]["train"]["error"]
        finally:
            srv.shutdown()


# -- bounded capture helper -------------------------------------------------
class TestCaptureDeviceTrace:
    def test_standalone_bounded_capture(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.utils.profiler import capture_device_trace

        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.zeros((64, 64))
        float(f(x))
        out = str(tmp_path / "trace")
        cap = capture_device_trace(2, out)
        # no monitored fit in this process → context-manager form
        assert not isinstance(cap, str)
        with cap:
            for _ in range(4):
                float(f(x))
                cap.step()
        times = perf.load_trace_op_times(out)
        assert times, "no device events captured"

    def test_trace_feeds_op_table(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.utils.profiler import capture_device_trace

        f = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
        a, b = jnp.zeros((64, 64)), jnp.zeros((64, 64))
        c = f.lower(a, b).compile()
        float(c(a, b))
        out = str(tmp_path / "trace")
        with capture_device_trace(1, out) as cap:
            float(c(a, b))
            cap.step()
        report = perf.build_report(c, name="probe", trace_dir=out)
        assert any(r["time_source"] == "trace" for r in report["ops"])
