"""Multi-process pod suite: N REAL OS processes on the CPU backend.

Two harness modes (distributed.podtest):

  * coordinated — real `jax.distributed.initialize` (die-together):
    bring-up hardening, eager collectives over the coordination KV, the
    multi-host checkpoint gates (writer-only quarantine, single-process-
    gated dedup), 3D-layout Model.fit per rank.
  * elastic — the shrink-and-continue supervisor (elastic.launch_elastic):
    rank-loss chaos drills where the pod must SURVIVE a SIGKILL, roll
    back in memory, and keep training.

Multi-process tests are `pod + slow` (run via tools/pod_smoke.sh —
spawning jax interpreters is seconds each, too heavy for tier-1); the
pure-logic failure-detector / coordinator / address-validation tests are
`pod` only and ride in tier-1 as well.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed import podcoll
from paddle_tpu.distributed.elastic import (ElasticResult, FAILURE_REASONS,
                                            PodRuntime)
from paddle_tpu.distributed.parallel import (CoordinatorAddressError,
                                             _validate_coordinator_address)
from paddle_tpu.distributed.podcoord import (DEAD_EXIT, DEAD_HEARTBEAT,
                                             DEAD_PARTITION,
                                             FailureDetector, PodClient,
                                             PodCoordinator, PodPeerLost)
from paddle_tpu.distributed.podtest import run_elastic_pod, run_pod

from conftest import cpu_subprocess_env

pytestmark = pytest.mark.pod

mp = pytest.mark.slow  # multi-process: excluded from tier-1, pod_smoke runs it


# ---------------------------------------------------------------------------
# pure-logic units (tier-1 speed)
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestFailureDetector:
    def test_timeout_boundary_is_strict(self):
        clk = FakeClock()
        det = FailureDetector(2, timeout_s=5.0, clock=clk)
        det.beat(0)
        det.beat(1)
        clk.advance(5.0)  # exactly the budget: still live
        assert det.check() == {}
        assert det.live() == [0, 1]
        det.beat(0)
        clk.advance(0.1)  # rank 1 is now past it
        assert det.check() == {1: DEAD_HEARTBEAT}
        assert det.live() == [0]
        # a second check reports nothing NEW
        assert det.check() == {}

    def test_bringup_grace_for_never_beaten_rank(self):
        clk = FakeClock()
        det = FailureDetector(2, timeout_s=2.0, clock=clk,
                              bringup_timeout_s=60.0)
        det.beat(0)
        clk.advance(10.0)
        # rank 1 never beat: it is still importing jax — only rank 0,
        # which DID beat and then went silent, is declared dead
        assert det.check() == {0: DEAD_HEARTBEAT}
        clk.advance(55.0)  # 65s > bring-up budget
        assert det.check() == {1: DEAD_HEARTBEAT}

    def test_bringup_default_is_at_least_steady_timeout(self):
        det = FailureDetector(1, timeout_s=300.0)
        assert det.bringup_timeout_s >= det.timeout_s

    def test_dead_rank_cannot_resurrect(self):
        clk = FakeClock()
        det = FailureDetector(2, timeout_s=1.0, clock=clk)
        det.declare_dead(1, DEAD_EXIT)
        det.beat(1, step=7)  # a zombie's late beat must be ignored
        assert det.live() == [0]
        assert det.dead() == {1: DEAD_EXIT}
        assert det.last_step(1) == -1

    def test_beat_records_step_progress(self):
        det = FailureDetector(1, timeout_s=1.0, clock=FakeClock())
        det.beat(0, step=3)
        det.beat(0, step=5)
        assert det.last_step(0) == 5


class TestCoordinatorAddressValidation:
    @pytest.mark.parametrize("bad", [
        "", "nohost", "localhost:", ":8080", "host:port",
        "host:0", "host:65536", "host:-1",
    ])
    def test_malformed_addresses_raise_named_error(self, bad):
        with pytest.raises(CoordinatorAddressError):
            _validate_coordinator_address(bad)

    def test_named_error_is_a_config_error_not_transient(self):
        # the retry loop retries ConnectionError/OSError/RuntimeError;
        # a malformed address must NOT be in that class
        assert issubclass(CoordinatorAddressError, ValueError)
        assert not issubclass(CoordinatorAddressError,
                              (ConnectionError, OSError))

    @pytest.mark.parametrize("good", [
        "127.0.0.1:8080", "localhost:1", "[::1]:6007", "host.name:65535",
    ])
    def test_valid_addresses_pass_through(self, good):
        assert _validate_coordinator_address(good) == good


class TestPodCoordinatorInProcess:
    """Real coordinator + clients over localhost TCP, one process."""

    def test_kv_barrier_and_epoch(self):
        with PodCoordinator(2, heartbeat_timeout_s=30.0) as coord:
            c0 = PodClient(coord.address, 0)
            c1 = PodClient(coord.address, 1)
            c0.kv_set("k", b"v")
            assert c1.kv_get("k") == b"v"
            c1.kv_delete("k")
            assert c0.kv_get("k", timeout_s=0.1) is None
            done = []
            t = threading.Thread(
                target=lambda: done.append(c1.barrier("b0")))
            t.start()
            r0 = c0.barrier("b0")
            t.join(timeout=10)
            assert done and done[0]["ok"] and r0["ok"]
            # no membership change while waiting -> clean, epoch 0
            assert r0["epoch"] == 0 and r0["shrunk"] is False

    def test_gather_freezes_over_survivors_on_death(self):
        with PodCoordinator(2, heartbeat_timeout_s=30.0) as coord:
            c0 = PodClient(coord.address, 0)
            out = {}

            def _g():
                out["r"] = c0.gather("ar", 1, b"part0")
            t = threading.Thread(target=_g)
            t.start()
            time.sleep(0.2)  # rank 0 is parked waiting for rank 1
            coord.mark_dead(1, DEAD_EXIT)
            t.join(timeout=10)
            ranks, _metas, payloads, epoch, shrunk = out["r"]
            assert ranks == [0] and payloads == [b"part0"]
            assert epoch == 1 and shrunk is True
            assert coord.live() == [0]

    def test_dead_rank_is_rejected_from_collectives(self):
        with PodCoordinator(2, heartbeat_timeout_s=30.0) as coord:
            coord.mark_dead(1, DEAD_PARTITION)
            c1 = PodClient(coord.address, 1)
            with pytest.raises(PodPeerLost):
                c1.gather("ar", 1, b"zombie")

    def test_post_shrink_steady_state_reads_clean(self):
        """The bug class the epoch-delta design exists for: after ONE
        shrink, later collectives must NOT keep reporting shrunk."""
        with PodCoordinator(2, heartbeat_timeout_s=30.0) as coord:
            coord.mark_dead(1, DEAD_EXIT)
            c0 = PodClient(coord.address, 0)
            ranks, _m, _p, epoch, shrunk = c0.gather("ar", 1, b"x")
            assert ranks == [0] and epoch == 1
            # caller arrived AFTER the death: epoch did not move while
            # it waited, so steady state is clean
            assert shrunk is False
            r = c0.barrier("b1")
            assert r["shrunk"] is False and r["epoch"] == 1


class _FakeTransport:
    """Scripted transport: drives PodGroup's epoch-delta latch."""
    elastic = True

    def __init__(self):
        self.rank, self.world = 0, 2
        self.epoch = 0
        self.ranks = [0, 1]

    def gather(self, name, seq, part, timeout_s=30.0):
        return list(self.ranks), [part] * len(self.ranks), self.epoch

    def barrier(self, name, timeout_s=30.0):
        return self.epoch

    def live(self):
        return list(self.ranks)


class TestPodGroupShrinkLatch:
    def test_epoch_advance_latches_once(self):
        tr = _FakeTransport()
        g = podcoll.PodGroup(tr)
        g.all_reduce(np.ones(2))
        assert g.consume_shrunk() is False
        # death between steps: the NEXT collective carries the new epoch
        tr.epoch, tr.ranks = 1, [0]
        g.all_reduce(np.ones(2))
        assert g.last_ranks == [0]
        assert g.consume_shrunk() is True
        # steady state afterwards is clean — no infinite replay
        g.all_reduce(np.ones(2))
        g.barrier()
        assert g.consume_shrunk() is False

    def test_all_reduce_mean_divides_by_live_contributors(self):
        tr = _FakeTransport()
        g = podcoll.PodGroup(tr)
        assert float(g.all_reduce_mean(np.array([4.0]))[0]) == 4.0
        tr.epoch, tr.ranks = 1, [0]
        # one survivor: mean == its own contribution (shrunk-from-start)
        assert float(g.all_reduce_mean(np.array([6.0]))[0]) == 6.0


class TestElasticResultAccounting:
    def test_survivors_ok_ignores_declared_dead_ranks(self):
        res = ElasticResult([0, -9], {1: (DEAD_EXIT, 123.0)},
                            [{"kind": "resumed", "t": 124.0,
                              "data": {"recovery_s": 0.25}, "rank": 0}],
                            [1.0], None)
        assert res.survivors_ok
        assert res.recovery_s() == 0.25
        assert len(res.resumed()) == 1

    def test_failure_reasons_include_elastic_class(self):
        assert "rank_lost_shrunk" in FAILURE_REASONS
        assert "crash" in FAILURE_REASONS

    def test_pod_runtime_requires_a_group(self):
        with pytest.raises(RuntimeError, match="pod group"):
            PodRuntime(group=None)


# ---------------------------------------------------------------------------
# coordinated mode: real jax.distributed.initialize, N processes
# ---------------------------------------------------------------------------

COORD_COLLECTIVES = """
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
import jax
assert jax.process_count() == WORLD, jax.process_count()
t = paddle.to_tensor(np.full((3,), float(RANK + 1), dtype="float32"))
dist.all_reduce(t)
red = t.numpy().tolist()
gathered = []
dist.all_gather(gathered, paddle.to_tensor(
    np.array([float(RANK)], dtype="float32")))
ag = [g.numpy().tolist() for g in gathered]
b = paddle.to_tensor(np.array([7.0 if RANK == 0 else -1.0],
                              dtype="float32"))
dist.broadcast(b, src=0)
dist.barrier()
emit(rank=RANK, red=red, ag=ag, bcast=b.numpy().tolist())
"""


@mp
class TestCoordinatedPod:
    def test_bringup_and_eager_collectives(self):
        res = run_pod(COORD_COLLECTIVES, world=2).assert_ok()
        for r in range(2):
            assert res.record(r, "red") == [3.0, 3.0, 3.0]  # 1+2
            assert res.record(r, "ag") == [[0.0], [1.0]]
            assert res.record(r, "bcast") == [7.0]

    def test_init_flaky_dials_are_retried_and_counted(self):
        src = """
import paddle_tpu.distributed as dist
from paddle_tpu.utils.metrics import default_registry

env = dist.init_parallel_env()
import jax
n = default_registry().get("paddle_launch_init_retries_total").get()
emit(rank=RANK, procs=jax.process_count(), retries=n)
"""
        res = run_pod(src, world=2,
                      env={"PADDLE_CHAOS_INIT_FLAKY": "2"}).assert_ok()
        for r in range(2):
            # both injected ConnectionErrors were retried, then the real
            # dial went through — bring-up survived the flake
            assert res.record(r, "procs") == 2
            assert res.record(r, "retries") == 2

    def test_fit_3d_layout_inside_pod_rank(self):
        """Each pod rank trains over its LOCAL dp*fsdp*tp mesh (8 virtual
        CPU devices) while jax.process_count()==2 — the v4 topology shape
        where the model-parallel axes stay inside a host."""
        src = """
import jax
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.layout import SpecLayout
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.io import TensorDataset
from paddle_tpu.hapi.callbacks import Callback

env = dist.init_parallel_env()
assert jax.process_count() == WORLD
mesh = build_mesh({"dp": 2, "fsdp": 2, "tp": 2},
                  devices=jax.local_devices())
paddle.seed(0)
net = paddle.nn.Linear(8, 8)
model = paddle.Model(net)
model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters()),
              paddle.nn.MSELoss())
rs = np.random.RandomState(0)
x = rs.randn(32, 8).astype("float32")
y = rs.randn(32, 8).astype("float32")
losses = []
class Rec(Callback):
    def on_train_batch_end(self, step, logs=None):
        losses.append(float(logs["loss"]))
model.fit(TensorDataset([x, y]), batch_size=8, epochs=1, shuffle=False,
          verbose=0, mesh=mesh, layout=SpecLayout(), callbacks=[Rec()])
emit(rank=RANK, losses=losses)
"""
        res = run_pod(src, world=2, local_devices=8,
                      timeout=240).assert_ok()
        l0, l1 = res.record(0, "losses"), res.record(1, "losses")
        assert len(l0) == 4 and np.all(np.isfinite(l0))
        # same data, same seed, deterministic: ranks agree exactly
        assert l0 == l1

    def test_checkpoint_writer_process_gate(self):
        """save_sharded + CheckpointManager on a REAL 2-process pod:
        process 0 is the only writer, every process restores."""
        src = """
import os
import numpy as np
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import podcoll
from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                               restore_sharded,
                                               save_sharded)

env = dist.init_parallel_env()
import jax
g = podcoll.default_group()
state = {"w": np.arange(6, dtype=np.float32) + 100 * 0}  # same on all
path = os.path.join(os.getcwd(), "shared-ckpt")
ret = save_sharded(state, path)
g.barrier()  # rank 0's write is durable before anyone reads
back = restore_sharded(path, template=state)
wrote_manifest = os.path.exists(os.path.join(path, "MANIFEST.json"))

mdir = os.path.join(os.getcwd(), "shared-mgr")
mgr = CheckpointManager(mdir)
assert mgr._single_process is False
assert mgr._is_writer_process == (RANK == 0)
ok = mgr.save(1, state, force=True)
g.barrier()
step, mback = mgr.restore_latest(template=state)
emit(rank=RANK, ok=bool(ok), step=step,
     round_trip=bool(np.array_equal(back["w"], state["w"])),
     mgr_round_trip=bool(np.array_equal(mback["w"], state["w"])),
     manifest=wrote_manifest)
"""
        res = run_pod(src, world=2, timeout=240).assert_ok()
        for r in range(2):
            # non-writer's save() returns True WITHOUT writing; both
            # ranks restore the same bytes through the shared path
            assert res.record(r, "ok") is True
            assert res.record(r, "step") == 1
            assert res.record(r, "round_trip") is True
            assert res.record(r, "mgr_round_trip") is True

    def test_checkpoint_dedup_is_single_process_gated(self):
        """On a pod the already-committed dedup check is SKIPPED (shared-
        storage visibility can skew across hosts): a second save of the
        same step rewrites instead of returning False."""
        src = """
import os
import numpy as np
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import podcoll
from paddle_tpu.distributed.checkpoint import COMMIT_NAME, CheckpointManager

env = dist.init_parallel_env()
g = podcoll.default_group()
mgr = CheckpointManager(os.path.join(os.getcwd(), "dedup-ckpt"))
state = {"w": np.ones(4, dtype=np.float32)}
first = mgr.save(2, state)
g.barrier()
commit = os.path.join(mgr._gen_dir(2), COMMIT_NAME)
m0 = os.path.getmtime(commit) if RANK == 0 else None
g.barrier()
second = mgr.save(2, state)  # force=False: single-process would dedup
g.barrier()
m1 = os.path.getmtime(commit) if RANK == 0 else None
emit(rank=RANK, first=bool(first), second=bool(second),
     rewrote=(None if RANK != 0 else bool(m1 > m0)))
"""
        res = run_pod(src, world=2, timeout=240).assert_ok()
        for r in range(2):
            assert res.record(r, "first") is True
            assert res.record(r, "second") is True
        assert res.record(0, "rewrote") is True

    def test_quarantine_is_writer_process_only(self):
        """A non-writer that trips over a corrupt generation cascades
        past it IN MEMORY; only process 0 renames it aside."""
        src = """
import glob, os
import numpy as np
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import podcoll
from paddle_tpu.distributed.checkpoint import CheckpointManager

env = dist.init_parallel_env()
g = podcoll.default_group()
d = os.path.join(os.getcwd(), "quar-ckpt")
mgr = CheckpointManager(d)
if RANK == 0:
    mgr.save(1, {"w": np.ones(4, dtype=np.float32)}, force=True)
    mgr.save(2, {"w": np.ones(4, dtype=np.float32) * 2}, force=True)
    # truncate a payload of the NEWEST generation: verify must reject it
    leaves = sorted(glob.glob(os.path.join(mgr._gen_dir(2),
                                           "leaves", "*")))
    with open(leaves[0], "r+b") as f:
        f.truncate(1)
g.barrier()
if RANK == 1:
    step, _ = mgr.restore_latest(template={"w": np.ones(4, "float32")})
    gen2_alive = os.path.isdir(mgr._gen_dir(2))
    quarantined = [n for n, _ in mgr.quarantined()]
    emit(rank=RANK, step=step, gen2_alive=gen2_alive,
         quarantined=quarantined)
g.barrier()  # rank 1's in-memory cascade happens BEFORE rank 0 renames
if RANK == 0:
    step, _ = mgr.restore_latest(template={"w": np.ones(4, "float32")})
    emit(rank=RANK, step=step, gen2_alive=os.path.isdir(mgr._gen_dir(2)),
         quarantined=[n for n, _ in mgr.quarantined()])
g.barrier()
"""
        res = run_pod(src, world=2, timeout=240).assert_ok()
        # non-writer: fell back to gen 1 but did NOT touch the bad dir
        assert res.record(1, "step") == 1
        assert res.record(1, "gen2_alive") is True
        assert res.record(1, "quarantined") == []
        # writer: same fallback, but gen 2 is renamed into quarantine/
        assert res.record(0, "step") == 1
        assert res.record(0, "gen2_alive") is False
        assert any(n.startswith("2.") for n in res.record(0, "quarantined"))


# ---------------------------------------------------------------------------
# elastic mode: shrink-and-continue chaos drills
# ---------------------------------------------------------------------------

ELASTIC_FIT = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed.elastic import PodRuntime
from paddle_tpu.io import TensorDataset
from paddle_tpu.hapi.callbacks import Callback

paddle.seed(0)
net = paddle.nn.Linear(4, 2)
model = paddle.Model(net)
model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters()),
              paddle.nn.MSELoss())
rs = np.random.RandomState(0)
x = rs.randn(48, 4).astype("float32")
y = rs.randn(48, 2).astype("float32")
losses = []
class Rec(Callback):
    def on_train_batch_end(self, step, logs=None):
        losses.append(float(logs["loss"]))
pod = PodRuntime.from_env()
model.fit(TensorDataset([x, y]), batch_size=8, epochs=1, shuffle=False,
          verbose=0, pod=pod, callbacks=[Rec()], log_freq=1)
params = [float(np.asarray(p.numpy(), dtype=np.float64).sum())
          for p in net.parameters()]
emit(rank=RANK, losses=losses, shrinks=pod.shrink_events, params=params)
pod.close()
"""

BASELINE_FIT = """
import json, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.io import TensorDataset
from paddle_tpu.hapi.callbacks import Callback

paddle.seed(0)
net = paddle.nn.Linear(4, 2)
model = paddle.Model(net)
model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters()),
              paddle.nn.MSELoss())
rs = np.random.RandomState(0)
x = rs.randn(48, 4).astype("float32")
y = rs.randn(48, 2).astype("float32")
losses = []
class Rec(Callback):
    def on_train_batch_end(self, step, logs=None):
        losses.append(float(logs["loss"]))
model.fit(TensorDataset([x, y]), batch_size=8, epochs=1, shuffle=False,
          verbose=0, callbacks=[Rec()])
params = [float(np.asarray(p.numpy(), dtype=np.float64).sum())
          for p in net.parameters()]
print("BASE " + json.dumps({"losses": losses, "params": params}))
"""


@pytest.fixture(scope="module")
def single_process_baseline():
    """The full-batch single-process run every parity drill compares
    against (one subprocess for the whole module)."""
    out = subprocess.run(
        [sys.executable, "-c", BASELINE_FIT], env=cpu_subprocess_env(),
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stdout + out.stderr
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("BASE ")]
    return json.loads(line[0][5:])


@mp
class TestElasticPod:
    def test_two_rank_fit_parity_without_chaos(self, single_process_baseline):
        res, pr = run_elastic_pod(ELASTIC_FIT, world=2, timeout=240)
        pr.assert_ok()
        assert res.deaths == {} and res.downs == []
        l0 = np.asarray(pr.record(0, "losses"))
        l1 = np.asarray(pr.record(1, "losses"))
        assert pr.record(0, "shrinks") == []
        assert pr.record(1, "shrinks") == []
        # each rank reports its half-batch loss; with equal halves the
        # full-batch MSE is their mean, and the averaged gradients give
        # the full-batch parameter trajectory on every rank
        base = single_process_baseline
        np.testing.assert_allclose((l0 + l1) / 2, base["losses"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(pr.record(0, "params"), base["params"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(pr.record(1, "params"),
                                   pr.record(0, "params"), rtol=0, atol=0)

    def test_rank_kill_mid_fit_shrinks_and_continues(self):
        """The tentpole drill: SIGKILL rank 1 at step 2, survivor rolls
        back in memory, re-strides, and finishes every step."""
        res, pr = run_elastic_pod(
            ELASTIC_FIT, world=2,
            env={"PADDLE_CHAOS_RANK_KILL": "1@2"}, timeout=240)
        assert res.returncodes[0] == 0
        assert res.returncodes[1] == -9  # really SIGKILLed
        assert res.survivors_ok
        assert res.deaths[1][0] == DEAD_EXIT
        shrinks = pr.record(0, "shrinks")
        assert len(shrinks) == 1 and shrinks[0]["live"] == [0]
        losses = pr.record(0, "losses")
        assert len(losses) == 6 and np.all(np.isfinite(losses))
        # the death->resumed gap was measured and is in-memory fast
        assert res.downs and res.recovery_s() is not None
        assert res.recovery_s() < 30.0

    def test_shrink_replay_matches_shrunk_from_start_bitwise(
            self, single_process_baseline):
        """Kill rank 1 before the first update: the survivor's replayed
        run IS a single-process full-batch run — bitwise, not approx
        (the ISSUE's ULP acceptance gate)."""
        res, pr = run_elastic_pod(
            ELASTIC_FIT, world=2,
            env={"PADDLE_CHAOS_RANK_KILL": "1@1"}, timeout=240)
        assert res.survivors_ok and res.returncodes[1] == -9
        losses = pr.record(0, "losses")
        base = single_process_baseline["losses"]
        assert losses == base, (
            "shrink-replay diverged from shrunk-from-start:\n"
            f"  elastic : {losses}\n  baseline: {base}")
        assert pr.record(0, "params") == single_process_baseline["params"]

    def test_slow_rank_is_not_a_false_positive(self):
        """A rank stalled longer than the heartbeat timeout must NOT be
        declared dead: the background heartbeat thread keeps beating
        through the stall."""
        res, pr = run_elastic_pod(
            ELASTIC_FIT, world=2,
            env={"PADDLE_CHAOS_RANK_SLOW": "1@3:2.5"},
            heartbeat_timeout_s=1.0, timeout=240)
        pr.assert_ok()
        assert res.deaths == {}
        assert pr.record(0, "shrinks") == []
        assert pr.record(1, "shrinks") == []
        assert len(pr.record(0, "losses")) == 6

    def test_partitioned_rank_is_fenced_and_pod_shrinks(self):
        """RANK_PARTITION stops rank 1's heartbeats while it keeps
        running (then stalls silently): the supervisor classifies it
        PARTITIONED, fences it with SIGKILL, and rank 0 continues."""
        res, pr = run_elastic_pod(
            ELASTIC_FIT, world=2,
            env={"PADDLE_CHAOS_RANK_PARTITION": "1@2",
                 "PADDLE_CHAOS_RANK_SLOW": "1@3:20"},
            heartbeat_timeout_s=1.5, timeout=240)
        assert res.deaths.get(1, ("",))[0] == DEAD_PARTITION
        assert res.returncodes[1] == -9  # fenced, not exited
        assert res.returncodes[0] == 0 and res.survivors_ok
        shrinks = pr.record(0, "shrinks")
        assert len(shrinks) == 1 and shrinks[0]["live"] == [0]
        assert len(pr.record(0, "losses")) == 6

    def test_sigkilled_rank_leaves_jsonl_for_goodput(self, tmp_path):
        """The flightrec contract for SIGKILL: no dump (atexit never
        runs), but the per-step events.jsonl stream survives, and the
        goodput ledger ingests it alongside the supervisor's measured
        down-time."""
        tdir = str(tmp_path / "telemetry")
        res, pr = run_elastic_pod(
            ELASTIC_FIT, world=2,
            env={"PADDLE_CHAOS_RANK_KILL": "1@3"},
            telemetry_dir=tdir, timeout=240)
        assert res.survivors_ok and res.returncodes[1] == -9
        rank1 = os.path.join(tdir, "rank1")
        assert os.path.exists(os.path.join(rank1, "events.jsonl"))
        assert not [f for f in os.listdir(rank1)
                    if f.startswith("flightrec-")]
        # the killed rank got far enough (log_freq=1) to leave window
        # wall-time the JSONL fallback can account as goodput
        with open(os.path.join(rank1, "events.jsonl")) as f:
            kinds = [json.loads(ln).get("event") for ln in f if ln.strip()]
        assert "window" in kinds
        assert res.report is not None
        assert res.report["seconds"]["down"] > 0
        assert res.report["sources"] >= 2
        assert res.report["seconds"]["productive_train"] > 0
        assert 0 < res.report["goodput_ratio"] < 1
