"""utils/profiler.py coverage: RecordEvent elapsed/nesting, StepTimers
accumulation + reset, chrome-trace export, Profiler start/stop
idempotence, and the logger-not-print satellite contract."""
import json
import logging
import time

import pytest

import paddle_tpu  # noqa: F401 - jax compat shims
from paddle_tpu.utils import profiler as prof


class TestRecordEvent:
    def test_elapsed_measures_scope(self):
        with prof.RecordEvent("t.scope") as ev:
            time.sleep(0.01)
        assert ev.elapsed >= 0.009
        assert ev.name == "t.scope"

    def test_nesting(self):
        with prof.RecordEvent("outer") as outer:
            with prof.RecordEvent("inner") as inner:
                time.sleep(0.002)
        assert inner.elapsed <= outer.elapsed
        assert inner.elapsed >= 0.001

    def test_exception_propagates_and_still_times(self):
        ev = prof.RecordEvent("boom")
        with pytest.raises(ValueError):
            with ev:
                raise ValueError("boom")
        assert ev.elapsed >= 0.0


class TestStepTimers:
    def test_accumulates_totals_and_counts(self):
        t = prof.StepTimers()
        for _ in range(3):
            with t.scope("data"):
                time.sleep(0.001)
        with t.scope("dispatch"):
            pass
        s = t.summary()
        assert s["data"]["count"] == 3
        assert s["data"]["total_s"] >= 0.002
        assert s["dispatch"]["count"] == 1

    def test_reset_zeroes_accumulators(self):
        """Per-epoch phase summaries must not accumulate forever."""
        t = prof.StepTimers()
        with t.scope("data"):
            pass
        assert t.summary()
        t.reset()
        assert t.summary() == {}
        assert t.totals == {} and t.counts == {}
        # usable after reset
        with t.scope("sync"):
            pass
        assert t.summary()["sync"]["count"] == 1


class TestChromeTraceExport:
    def test_export_path(self, tmp_path):
        """Host RecordEvent scopes land in chrome://tracing JSON when the
        native core is available; without it the export reports failure
        (negative) instead of writing garbage."""
        from paddle_tpu import core

        path = str(tmp_path / "trace.json")
        core.trace_clear()
        core.profiler_enable(True)
        try:
            with prof.RecordEvent("outer"):
                with prof.RecordEvent("inner"):
                    time.sleep(0.001)
        finally:
            core.profiler_enable(False)
        n = prof.export_chrome_trace(path)
        if not core.available():
            assert n < 0
            return
        assert n == 2
        events = json.load(open(path))["traceEvents"]
        names = {e.get("name") for e in events}
        assert {"outer", "inner"} <= names


class TestProfilerFacade:
    @pytest.fixture
    def recorded(self, monkeypatch):
        calls = []
        monkeypatch.setattr(prof, "start_profiler",
                            lambda *a, **k: calls.append("start"))
        monkeypatch.setattr(prof, "stop_profiler",
                            lambda *a, **k: calls.append("stop"))
        return calls

    def test_start_stop_idempotent(self, recorded):
        p = prof.Profiler()
        p.start()
        p.start()  # second start must NOT start a second trace
        assert recorded == ["start"]
        p.stop()
        p.stop()   # second stop is a no-op
        assert recorded == ["start", "stop"]

    def test_disabled_profiler_never_starts(self, recorded):
        p = prof.Profiler(enabled=False)
        p.start()
        p.stop()
        assert recorded == []

    def test_context_manager(self, recorded):
        with prof.Profiler():
            pass
        assert recorded == ["start", "stop"]

    def test_options_unknown_key_raises(self):
        with pytest.raises(ValueError):
            prof.ProfilerOptions()["no_such_option"]


class TestLoggerNotPrint:
    def test_stop_profiler_routes_through_logger(self, tmp_path, capsys,
                                                 caplog, monkeypatch):
        """The user-facing print() calls in stop_profiler were replaced
        by the module logger (paddle_tpu.hapi logger pattern)."""
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        with caplog.at_level(logging.INFO, logger="paddle_tpu.profiler"):
            prof.start_profiler(str(tmp_path))
            prof.stop_profiler(profile_path=str(tmp_path))
        assert capsys.readouterr().out == ""
        assert any("profiler trace written" in r.message
                   for r in caplog.records)
