"""PostTrainingQuantization (reference: fluid/contrib/slim/quantization/
post_training_quantization.py:120): calibration-only int8 — observer
statistics, threshold algorithms, channel-wise weight scales, accuracy
within budget of fp32, and the int8 export artifact."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.slim import (PostTrainingQuantization,
                             load_quantized_predictor)
from paddle_tpu.slim import _ActObserver, _PTQWrapper  # noqa: internals

rs = np.random.RandomState(0)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.relu = nn.ReLU()
        self.fc2 = nn.Linear(32, 2)

    def forward(self, x):
        return self.fc2(self.relu(self.fc1(x)))


def _loader(n_batches=8, batch=16, d=8):
    for _ in range(n_batches):
        yield paddle.to_tensor(rs.randn(batch, d).astype(np.float32))


def test_observer_thresholds_ordered():
    obs = _ActObserver()
    for _ in range(16):
        obs.collect(paddle.to_tensor(
            rs.randn(1024).astype(np.float32)))
    t_max = obs.threshold("abs_max")
    t_avg = obs.threshold("avg")
    t_hist = obs.threshold("hist", hist_percent=0.999)
    t_kl = obs.threshold("KL")
    t_mse = obs.threshold("mse")
    # clipping algorithms must clip: thresholds below the global abs-max,
    # but positive and of the right magnitude for N(0,1) data
    assert 0 < t_avg <= t_max
    assert 0.5 < t_hist < t_max
    assert 0.5 < t_kl <= t_max + 1e-6
    assert 0.5 < t_mse <= t_max + 1e-6


def test_observer_rebinning_keeps_mass():
    obs = _ActObserver()
    obs.collect(paddle.to_tensor(np.full(100, 0.5, np.float32)))
    mass1 = obs.hist.sum()
    # a 10x larger batch forces a histogram re-bin
    obs.collect(paddle.to_tensor(np.full(50, 5.0, np.float32)))
    assert obs.hist_max == pytest.approx(5.0)
    assert obs.hist.sum() == pytest.approx(mass1 + 50)


def test_ptq_accuracy_close_to_fp32():
    paddle.seed(7)
    model = MLP()
    x_eval = rs.randn(64, 8).astype(np.float32)
    want = np.asarray(model(paddle.to_tensor(x_eval)).numpy())

    ptq = PostTrainingQuantization(model, _loader(), batch_nums=8,
                                   algo="hist")
    qmodel = ptq.quantize()
    got = np.asarray(qmodel(paddle.to_tensor(x_eval)).numpy())
    # int8 budget: small relative error on the logits
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-8)
    assert rel < 0.1, rel
    # wrapped layers replaced in place
    assert isinstance(qmodel.fc1, _PTQWrapper)
    assert isinstance(qmodel.fc2, _PTQWrapper)


@pytest.mark.parametrize("algo", ["abs_max", "avg", "hist", "KL", "mse"])
def test_ptq_all_algos_run(algo):
    paddle.seed(1)
    model = MLP()
    q = PostTrainingQuantization(model, _loader(4), batch_nums=4,
                                 algo=algo).quantize()
    out = q(paddle.to_tensor(rs.randn(4, 8).astype(np.float32)))
    assert np.isfinite(np.asarray(out.numpy())).all()


def test_channel_wise_weight_scales():
    paddle.seed(2)
    net = nn.Sequential(nn.Conv2D(2, 6, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(6 * 4 * 4, 3))

    def loader():
        for _ in range(3):
            yield paddle.to_tensor(rs.randn(2, 2, 4, 4).astype(np.float32))

    q = PostTrainingQuantization(
        net, loader(), batch_nums=3,
        weight_quantize_type="channel_wise_abs_max").quantize()
    conv_scale = np.asarray(q[0].weight_scale.numpy())
    fc_scale = np.asarray(q[3].weight_scale.numpy())
    assert conv_scale.shape == (6, 1, 1, 1)   # per out-channel (OIHW)
    assert fc_scale.shape == (1, 3)           # per out-feature ([in, out])
    assert (conv_scale > 0).all() and (fc_scale > 0).all()


def test_ptq_export_int8_artifact(tmp_path):
    paddle.seed(3)
    model = MLP()
    x = rs.randn(4, 8).astype(np.float32)
    ptq = PostTrainingQuantization(model, _loader(4), batch_nums=4,
                                   algo="avg")
    qmodel = ptq.quantize()
    want = np.asarray(qmodel(paddle.to_tensor(x)).numpy())
    prefix = str(tmp_path / "ptq_model")
    ptq.save_quantized_model(prefix, example_inputs=[x])
    pred = load_quantized_predictor(prefix)
    got, = pred.run([x])
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)
    for rec in pred.quant_params.values():
        assert rec["int8_weight"].dtype == np.int8
        assert rec["act_scale"] > 0


def test_ptq_requires_quantizable_layers():
    with pytest.raises(ValueError):
        PostTrainingQuantization(nn.ReLU(), _loader(1)).quantize()


def test_ptq_requires_calibration_batches():
    """Regression: no loader (or an empty one) must raise, not silently
    substitute weight magnitudes for activation scales."""
    with pytest.raises(ValueError, match="calibration"):
        PostTrainingQuantization(MLP(), data_loader=None).quantize()
    with pytest.raises(ValueError, match="calibration"):
        PostTrainingQuantization(MLP(), data_loader=iter(())).quantize()
