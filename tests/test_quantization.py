"""slim/quantization tests (reference: contrib/slim/quantization/
quantization_pass.py + imperative/qat.py): QAT wrapping, STE gradients,
convergence, and the int8 export artifact served by the Predictor."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.slim import (QAT, QuantizedLinear, fake_quant,
                             load_quantized_predictor)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.relu = nn.ReLU()
        self.fc2 = nn.Linear(16, 2)

    def forward(self, x):
        return self.fc2(self.relu(self.fc1(x)))


class ConvNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(1, 4, 3, padding=1)
        self.fc = nn.Linear(4 * 4 * 4, 2)

    def forward(self, x):
        h = paddle.nn.functional.relu(self.conv(x))
        return self.fc(h.reshape([h.shape[0], -1]))


class TestFakeQuant:
    def test_rounds_to_grid(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 9).astype(np.float32))
        s = paddle.to_tensor(np.float32(1.0))
        q = np.asarray(fake_quant(x, s, bits=8).numpy())
        step = 1.0 / 127
        np.testing.assert_allclose(q / step, np.round(q / step),
                                   atol=1e-5)
        np.testing.assert_allclose(q, np.asarray(x.numpy()), atol=step)

    def test_ste_gradient_is_identity(self):
        x = paddle.to_tensor(np.array([0.3, -0.7], np.float32),
                             stop_gradient=False)
        s = paddle.to_tensor(np.float32(1.0))
        fake_quant(x, s).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])

    def test_saturates_at_scale(self):
        x = paddle.to_tensor(np.array([10.0], np.float32))
        s = paddle.to_tensor(np.float32(1.0))
        q = float(np.asarray(fake_quant(x, s, bits=8).numpy()))
        assert abs(q - 1.0) < 1e-5


class TestQATTransform:
    def test_wraps_quantizable_layers(self):
        net = MLP()
        QAT().quantize(net)
        assert isinstance(net.fc1, QuantizedLinear)
        assert isinstance(net.fc2, QuantizedLinear)
        assert isinstance(net.relu, nn.ReLU)  # untouched

    def test_observer_tracks_scale(self):
        net = MLP()
        QAT(moving_rate=0.0).quantize(net)  # rate 0: scale = last abs-max
        net.train()
        x = paddle.to_tensor(np.full((2, 8), 3.0, np.float32))
        net(x)
        np.testing.assert_allclose(
            float(np.asarray(net.fc1.act_scale.numpy())), 3.0, rtol=1e-5)

    def test_qat_converges_on_separable_data(self):
        paddle.seed(0)
        rng = np.random.RandomState(0)
        w_true = rng.randn(8, 1).astype(np.float32)
        X = rng.randn(256, 8).astype(np.float32)
        y = (X @ w_true > 0).astype(np.int64).reshape(-1)

        net = MLP()
        QAT().quantize(net)
        net.train()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        ce = nn.CrossEntropyLoss()
        for _ in range(60):
            logits = net(paddle.to_tensor(X))
            loss = ce(logits, paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
        net.eval()
        pred = np.asarray(net(paddle.to_tensor(X)).numpy()).argmax(1)
        acc = (pred == y).mean()
        assert acc > 0.9, f"QAT failed to converge, acc={acc}"


class TestInt8Export:
    def test_export_and_serve(self, tmp_path):
        paddle.seed(1)
        net = MLP()
        qat = QAT()
        qat.quantize(net)
        net.train()
        net(paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 8).astype(np.float32)))
        prefix = str(tmp_path / "qmodel")
        qat.save_quantized_model(
            net, prefix,
            example_inputs=[np.zeros((4, 8), np.float32)])

        assert os.path.exists(prefix + ".pdqparams")
        assert os.path.exists(prefix + ".pdexport")
        pred = load_quantized_predictor(prefix)
        x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        out, = pred.run([x])
        # served output matches the QAT model's eval forward
        net.eval()
        expect = np.asarray(net(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)
        # real int8 payload with sane scales
        q = pred.quant_params
        assert len(q) == 2
        for v in q.values():
            assert v["int8_weight"].dtype == np.int8
            assert v["weight_scale"] > 0

    def test_packed_int8_matches_served_numerics(self, tmp_path):
        # round-3 advisor finding: packing used the stale training-time
        # weight_scale buffer while the export trace fake-quantized with
        # the current abs-max — after a post-forward weight update the
        # payload would not reproduce the served numerics
        paddle.seed(2)
        net = MLP()
        qat = QAT()
        qat.quantize(net)
        net.train()
        net(paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 8).astype(np.float32)))
        # simulate an optimizer step AFTER the last training forward
        w = net.fc1.inner.weight
        w.set_value(np.asarray(w.numpy()) * 1.7)
        prefix = str(tmp_path / "qmodel2")
        qat.save_quantized_model(
            net, prefix, example_inputs=[np.zeros((4, 8), np.float32)])
        pred = load_quantized_predictor(prefix)
        rec = pred.quant_params["fc1"]
        qmax = 2 ** (rec["bits"] - 1) - 1
        dq = rec["int8_weight"].astype(np.float32) * \
            (max(rec["weight_scale"], 1e-8) / qmax)
        # dequantized payload must equal the fake-quantized weight the
        # export trace baked in (i.e. current abs-max scale, not stale)
        wq = np.asarray(net.fc1.inner.weight.numpy())
        scale = np.max(np.abs(wq))
        step = max(scale, 1e-8) / qmax
        expect = np.clip(np.round(wq / step), -qmax, qmax) * step
        np.testing.assert_allclose(dq, expect, rtol=1e-6, atol=1e-7)

    def test_conv_qat_smoke(self, tmp_path):
        net = ConvNet()
        QAT().quantize(net)
        from paddle_tpu.slim import QuantizedConv2D
        assert isinstance(net.conv, QuantizedConv2D)
        net.train()
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 1, 4, 4).astype(np.float32))
        out = net(x)
        loss = (out ** 2).mean()
        loss.backward()
        assert out.shape[0] == 2
