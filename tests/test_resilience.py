"""Fault-tolerant runtime unit tests (distributed/resilience.py).

Every recovery path is driven by deterministic fault injection
(paddle_tpu.utils.chaos) — no mocks: the NaN policies see real NaN
losses, the watchdog sees a real stalled step, preemption is a real
SIGTERM latched by a real handler.
"""
import os
import random
import signal
import subprocess
import sys
import textwrap
import time

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.launch import _restart_delay
from paddle_tpu.distributed.resilience import (
    PREEMPTED_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
    PreemptionGuard,
    Watchdog,
    retry_with_backoff,
    run_resilient,
)
from paddle_tpu.utils import chaos

from conftest import cpu_subprocess_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestRetryWithBackoff:
    def test_success_first_try_no_sleep(self):
        sleeps = []
        out = retry_with_backoff(lambda: 42, sleep=sleeps.append)
        assert out == 42 and sleeps == []

    def test_fails_then_succeeds(self):
        sleeps, calls = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_with_backoff(flaky, retries=5, base_delay=0.1,
                                 jitter=0.0, sleep=sleeps.append)
        assert out == "ok"
        assert len(calls) == 3
        assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]

    def test_sleep_monotonic_and_capped(self):
        """Without jitter the delay sequence is exactly exponential,
        monotonically non-decreasing, and capped at max_delay."""
        sleeps = []

        def always_fails():
            raise OSError("nope")

        with pytest.raises(OSError):
            retry_with_backoff(always_fails, retries=6, base_delay=0.1,
                               max_delay=1.0, jitter=0.0,
                               sleep=sleeps.append)
        assert len(sleeps) == 6
        assert all(b >= a for a, b in zip(sleeps, sleeps[1:]))
        np.testing.assert_allclose(
            sleeps, [0.1, 0.2, 0.4, 0.8, 1.0, 1.0], rtol=1e-9)

    def test_jitter_bounds(self):
        """With jitter=j every delay lands in [d, d*(1+j))."""
        sleeps = []

        def always_fails():
            raise OSError("nope")

        with pytest.raises(OSError):
            retry_with_backoff(always_fails, retries=8, base_delay=0.1,
                               max_delay=100.0, jitter=0.5,
                               rng=random.Random(1234),
                               sleep=sleeps.append)
        for i, s in enumerate(sleeps):
            lo = 0.1 * (2 ** i)
            assert lo <= s < lo * 1.5, (i, s)

    def test_gives_up_raises_last_error(self):
        errs = [OSError("a"), OSError("b"), OSError("final")]

        def failing():
            raise errs[len(sleeps)]

        sleeps = []
        with pytest.raises(OSError, match="final"):
            retry_with_backoff(failing, retries=2, base_delay=0.0,
                               jitter=0.0, sleep=lambda d: sleeps.append(d))

    def test_unmatched_exception_not_retried(self):
        sleeps, calls = [], []

        def bad():
            calls.append(1)
            raise ValueError("logic bug, not transient")

        with pytest.raises(ValueError):
            retry_with_backoff(bad, retries=5, retry_on=(OSError,),
                               sleep=sleeps.append)
        assert len(calls) == 1 and sleeps == []


class TestPreemptionGuard:
    def test_latches_sigterm_and_restores_handler(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionGuard() as g:
            assert not g.preempted
            os.kill(os.getpid(), signal.SIGTERM)
            # delivery is synchronous at the next bytecode boundary
            assert g.preempted
            assert g.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before

    def test_latches_sigint(self):
        with PreemptionGuard() as g:
            os.kill(os.getpid(), signal.SIGINT)
            assert g.preempted and g.signum == signal.SIGINT


class TestWatchdog:
    def test_fires_on_hang(self):
        fired = []
        wd = Watchdog(0.2, on_timeout=fired.append, poll_interval=0.05)
        wd.start()
        time.sleep(0.6)  # no beat() — a hung step
        wd.stop()
        assert wd.fired and fired and fired[0] > 0.2

    def test_beats_prevent_firing(self):
        fired = []
        with Watchdog(0.5, on_timeout=fired.append,
                      poll_interval=0.05) as wd:
            for _ in range(8):
                time.sleep(0.1)
                wd.beat()
        assert not wd.fired and not fired

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError):
            Watchdog(0.0)


def _counting_step(step, state):
    """Toy step: one update per good step, constant finite loss."""
    return {"n": state["n"] + 1}, 0.5


class TestAnomalyPolicies:
    @pytest.mark.chaos
    def test_skip_drops_bad_steps(self):
        with chaos.inject(nan_at_step=(2, 4)):
            state, info = run_resilient(
                _counting_step, {"n": 0}, num_steps=5,
                anomaly_policy="skip", max_bad_steps=3)
        assert state["n"] == 3  # steps 2 and 4 skipped
        assert info["bad_steps"] == 2 and info["skipped_steps"] == 2

    @pytest.mark.chaos
    def test_skip_escalates_after_max_consecutive(self):
        with chaos.inject(nan_at_step=(2, 3, 4)):
            with pytest.raises(FloatingPointError, match="consecutive"):
                run_resilient(_counting_step, {"n": 0}, num_steps=6,
                              anomaly_policy="skip", max_bad_steps=3)

    @pytest.mark.chaos
    def test_halt_raises_immediately(self):
        with chaos.inject(nan_at_step=3):
            with pytest.raises(FloatingPointError, match="step 3"):
                run_resilient(_counting_step, {"n": 0}, num_steps=5,
                              anomaly_policy="halt")

    @pytest.mark.chaos
    def test_rollback_restores_checkpoint_and_replays(self, tmp_path):
        def step_fn(step, state):
            return {"n": state["n"] + 1.0}, 0.5

        with CheckpointManager(str(tmp_path / "rb")) as mgr:
            # nan at steps 3 and 4 → streak hits max_bad_steps=2 at step
            # 4 → roll back to the step-2 checkpoint and replay (the
            # injections are one-shot, like transient data corruption)
            with chaos.inject(nan_at_step=(3, 4)):
                state, info = run_resilient(
                    step_fn, {"n": jnp.float32(0)}, mgr, num_steps=6,
                    anomaly_policy="rollback", max_bad_steps=2,
                    save_interval=2)
        assert float(state["n"]) == 6.0
        assert info["rollbacks"] == 1 and info["bad_steps"] == 2

    def test_rollback_requires_manager(self):
        with pytest.raises(ValueError, match="rollback"):
            run_resilient(_counting_step, {"n": 0}, num_steps=2,
                          anomaly_policy="rollback")


class TestResumeAndPreemption:
    def test_auto_resume_from_latest(self, tmp_path):
        def step_fn(step, state):
            return {"n": state["n"] + 1.0}, None

        with CheckpointManager(str(tmp_path / "ar")) as mgr:
            mgr.save(3, {"n": jnp.float32(3)}, force=True)
            mgr.wait()
            state, info = run_resilient(step_fn, {"n": jnp.float32(0)},
                                        mgr, num_steps=5)
        assert info["resumed_step"] == 3
        assert float(state["n"]) == 5.0  # only steps 4 and 5 ran

    @pytest.mark.chaos
    def test_preemption_checkpoints_and_reports(self, tmp_path):
        def step_fn(step, state):
            return {"n": state["n"] + 1.0}, 0.1

        with CheckpointManager(str(tmp_path / "pre")) as mgr:
            with chaos.inject(preempt_at_step=2):
                state, info = run_resilient(
                    step_fn, {"n": jnp.float32(0)}, mgr, num_steps=50,
                    exit_on_preempt=False)
            assert info["preempted"] and info["last_step"] == 2
            assert mgr.latest_step() == 2  # the emergency checkpoint

    @pytest.mark.chaos
    def test_preemption_exits_with_distinct_code(self, tmp_path):
        def step_fn(step, state):
            return {"n": state["n"] + 1.0}, 0.1

        with CheckpointManager(str(tmp_path / "px")) as mgr:
            with chaos.inject(preempt_at_step=1):
                with pytest.raises(SystemExit) as ei:
                    run_resilient(step_fn, {"n": jnp.float32(0)}, mgr,
                                  num_steps=50)
            assert ei.value.code == PREEMPTED_EXIT_CODE

    @pytest.mark.chaos
    def test_watchdog_detects_chaos_slow_step(self):
        fired = []
        with chaos.inject(slow_step=2, slow_seconds=0.8):
            state, info = run_resilient(
                _counting_step, {"n": 0}, num_steps=3,
                watchdog_timeout=0.3,
                on_watchdog_timeout=fired.append)
        assert fired and fired[0] > 0.3
        assert state["n"] == 3  # custom on_timeout lets the run finish


class TestLauncherBackoff:
    def test_restart_delay_exponential_and_jittered(self):
        rng = random.Random(7)
        base = [_restart_delay(a, base=0.5, jitter=0.0) for a in (1, 2, 3, 4)]
        np.testing.assert_allclose(base, [0.5, 1.0, 2.0, 4.0])
        for a in (1, 2, 3):
            lo = 0.5 * (2 ** (a - 1))
            for _ in range(50):
                d = _restart_delay(a, base=0.5, jitter=0.5, rng=rng)
                assert lo <= d < lo * 1.5

    def test_restart_delay_capped(self):
        assert _restart_delay(50, base=1.0, max_delay=60.0,
                              jitter=0.0) == 60.0


class TestHapiFaultTolerance:
    """Model.fit(resume=/fault_tolerant=) — the high-level API gets the
    same crash-recovery contract as run_resilient."""

    def _model_and_data(self):
        import paddle_tpu as paddle
        from paddle_tpu.hapi import Model

        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(8, 2))
        rs = np.random.RandomState(0)
        x = rs.randn(32, 4).astype("float32")
        y = (x.sum(1) > 0).astype("int64")
        ds = paddle.io.TensorDataset([paddle.to_tensor(x),
                                      paddle.to_tensor(y)])
        model = Model(net)
        model.prepare(
            paddle.optimizer.Adam(learning_rate=0.01,
                                  parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        return model, ds

    @staticmethod
    def _weights(model):
        return {k: np.asarray(p._value)
                for k, p in model.network.named_parameters()}

    def test_fit_resume_bitwise_identical(self, tmp_path):
        # oracle: 4 uninterrupted epochs
        ma, ds = self._model_and_data()
        ma.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0)
        ref = self._weights(ma)

        # phase 1: 2 epochs, checkpointing each epoch end
        mb, ds = self._model_and_data()
        mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               resume=str(tmp_path))
        # phase 2: a FRESH process-equivalent model resumes and finishes
        mc, ds = self._model_and_data()
        mc.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0,
               resume=str(tmp_path))
        got = self._weights(mc)
        assert set(got) == set(ref)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    @pytest.mark.chaos
    def test_fit_preemption_emergency_checkpoint(self, tmp_path):
        model, ds = self._model_and_data()
        with chaos.inject(preempt_at_step=3):
            with pytest.raises(SystemExit) as ei:
                model.fit(ds, batch_size=8, epochs=4, shuffle=False,
                          verbose=0, fault_tolerant=True,
                          resume=str(tmp_path))
        assert ei.value.code == PREEMPTED_EXIT_CODE
        with CheckpointManager(
                os.path.join(str(tmp_path), "resilient")) as mgr:
            assert mgr.latest_step() == 3  # in-flight batch finished

    def test_fit_requires_directory(self):
        model, ds = self._model_and_data()
        with pytest.raises(ValueError, match="directory"):
            model.fit(ds, batch_size=8, epochs=1, verbose=0,
                      fault_tolerant=True)

    def test_fit_resume_bitwise_with_mid_epoch_checkpoint(self, tmp_path):
        """checkpoint_interval checkpoints come straight from the
        device-resident engine state mid-epoch; resuming from one is
        still bitwise-exact vs the uninterrupted run."""
        ma, ds = self._model_and_data()
        ma.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0)
        ref = self._weights(ma)

        # phase 1: 2 epochs (8 steps), checkpointing every 3 iterations —
        # the newest checkpoint lands MID-epoch at iteration 6
        mb, ds = self._model_and_data()
        mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               resume=str(tmp_path), checkpoint_interval=3)
        with CheckpointManager(
                os.path.join(str(tmp_path), "resilient")) as mgr:
            assert mgr.latest_step() == 6
        # phase 2: fresh process-equivalent resumes at 6 and finishes
        mc, ds = self._model_and_data()
        mc.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0,
               resume=str(tmp_path), checkpoint_interval=3)
        got = self._weights(mc)
        assert set(got) == set(ref)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    @pytest.mark.chaos
    def test_fit_preempt_resume_bitwise(self, tmp_path):
        """The emergency checkpoint written on preemption materializes
        the donated engine state; a restart resumes from it to the same
        bits as a never-preempted run."""
        ma, ds = self._model_and_data()
        ma.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0)
        ref = self._weights(ma)

        mb, ds = self._model_and_data()
        with chaos.inject(preempt_at_step=5):
            with pytest.raises(SystemExit) as ei:
                mb.fit(ds, batch_size=8, epochs=3, shuffle=False,
                       verbose=0, fault_tolerant=True, resume=str(tmp_path))
        assert ei.value.code == PREEMPTED_EXIT_CODE
        chaos.reset()
        mc, ds = self._model_and_data()
        mc.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0,
               resume=str(tmp_path))
        got = self._weights(mc)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    def test_ft_state_materializes_engine_state(self):
        """While the engine is live, _ft_state must return HOST numpy
        arrays (orbax saves async; the engine donates its device buffers
        on the next dispatch — handing it live device arrays would
        race), and the snapshot must survive a subsequent step."""
        import jax

        import paddle_tpu as paddle
        from paddle_tpu.hapi.engine import TrainEngine

        model, ds = self._model_and_data()
        eng = TrainEngine(model).begin()
        model._engine = eng
        snap = model._ft_state(7)
        leaves = jax.tree_util.tree_leaves(snap)
        assert leaves and all(
            isinstance(v, (np.ndarray, np.generic)) for v in leaves)
        assert int(snap["meta"]["it"]) == 7
        frozen = {k: np.array(v) for k, v in snap["params"].items()}
        rs = np.random.RandomState(0)
        eng.step([paddle.to_tensor(rs.randn(8, 4).astype("float32"))],
                 [paddle.to_tensor(rs.randint(0, 2, (8,))
                                   .astype("int64"))])
        for k in frozen:  # snapshot unaffected by the donated step
            np.testing.assert_array_equal(snap["params"][k], frozen[k])


@pytest.mark.chaos
class TestWatchdogSubprocess:
    def test_hung_step_aborts_with_watchdog_code(self, tmp_path):
        """A truly hung step (chaos slow-step >> timeout) must kill the
        process with the distinct watchdog exit code, not hang the pod."""
        script = tmp_path / "hung.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            sys.path.insert(0, %r)
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            from paddle_tpu.distributed.resilience import run_resilient

            def step_fn(step, state):
                return state, 0.1

            run_resilient(step_fn, {"n": 0}, num_steps=10,
                          watchdog_timeout=1.0)
            print("UNREACHABLE")
        """ % REPO))
        env = cpu_subprocess_env()
        env["PADDLE_CHAOS_SLOW_STEP"] = "2"
        env["PADDLE_CHAOS_SLOW_SECONDS"] = "300"
        r = subprocess.run([sys.executable, str(script)], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == WATCHDOG_EXIT_CODE, (r.returncode, r.stderr)
        assert "UNREACHABLE" not in r.stdout
        # the stack dump makes the hang attributable
        assert "watchdog" in r.stderr.lower() or "Thread" in r.stderr
