"""Ring/Ulysses sequence-parallel attention vs dense reference."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.ops.ring_attention import ring_attention, ulysses_attention


def _dense_reference(q, k, v, causal):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D ** 0.5)
    if causal:
        S = q.shape[1]
        mask = np.tril(np.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(rs, B=2, S=32, H=4, D=8):
    mk = lambda: jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    mesh = build_mesh({"sp": 8})
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    ref = _dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = build_mesh({"sp": 4}, devices=jax.devices()[:4])
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs, H=4)
    out = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    ref = _dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_grads_match_dense():
    mesh = build_mesh({"sp": 8})
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs, S=16)

    def loss_ring(q, k, v):
        return jnp.mean(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.mean(_dense_reference(q, k, v, True) ** 2)

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_bad_heads():
    mesh = build_mesh({"sp": 8})
    q = jnp.zeros((2, 16, 6, 8))
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, mesh)


def test_ring_in_hybrid_mesh():
    # sp composed with dp in one mesh: batch sharded dp, seq sharded sp
    mesh = build_mesh({"dp": 2, "sp": 4})
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, B=4, S=16)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    ref = _dense_reference(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
