"""paddle_tpu.serving — adaptive-batching serving engine tests.

Pins the four serving contracts (ISSUE 3 acceptance):
  * adaptive batching — flush on max_batch_size OR batch_timeout_ms,
    padded into shape buckets, responses bitwise-identical to a direct
    single-request Predictor.run (batched-vs-single parity)
  * zero XLA compilations after warmup — a compile tripwire on
    jax's compile entry point stays silent across concurrent traffic
    spanning multiple shape buckets
  * bounded-queue backpressure, deadlines, and cancellation
  * graceful SIGTERM drain (utils.chaos self-preemption): in-flight and
    queued requests complete, new work is rejected, clean exit
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, serving
from paddle_tpu.serving import (
    BucketSpec,
    DeadlineExceededError,
    EngineStoppedError,
    QueueFullError,
    ServingClient,
    ServingEngine,
    ServingServer,
)
from paddle_tpu.utils import chaos

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def exported_mlp(tmp_path_factory):
    """Symbolic-batch, symbolic-seq Linear stack: (B, S, 8) -> (B, S, 3).
    Row- and token-independent math, so padded slots cannot perturb real
    outputs — the bitwise parity oracle."""
    paddle.seed(7)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 3))
    net.eval()
    prefix = str(tmp_path_factory.mktemp("serving") / "mlp")
    from paddle_tpu.static import InputSpec
    inference.save_inference_model(
        prefix, net, input_spec=[InputSpec([-1, -1, 8], "float32")],
        example_inputs=[np.zeros((2, 4, 8), np.float32)])
    return prefix


def _sample(i, seq=4):
    return np.random.RandomState(i).randn(seq, 8).astype(np.float32)


class TestBucketSpec:
    def test_parse_batch_only(self):
        b = BucketSpec.parse("1,2,4,8")
        assert b.batch_sizes == [1, 2, 4, 8]
        assert b.seq_lens is None
        assert b.max_batch == 8
        assert b.batch_for(3) == 4
        assert b.batch_for(9) == 8  # clamped to largest
        assert b.seq_for(999) == 999  # pass-through without seq buckets

    def test_parse_batch_x_seq(self):
        b = BucketSpec.parse("1,4x16,32")
        assert b.batch_sizes == [1, 4]
        assert b.seq_lens == [16, 32]
        assert b.seq_for(10) == 16
        assert b.seq_for(17) == 32
        with pytest.raises(ValueError, match="exceeds"):
            b.seq_for(33)

    def test_powers_of_two(self):
        assert BucketSpec.powers_of_two(8).batch_sizes == [1, 2, 4, 8]
        assert BucketSpec.powers_of_two(6).batch_sizes == [1, 2, 4, 6]

    def test_invalid(self):
        with pytest.raises(ValueError):
            BucketSpec.parse("")
        with pytest.raises(ValueError):
            BucketSpec([0, 2])


class TestAdaptiveBatching:
    def test_timeout_flush_coalesces_partial_batch(self, exported_mlp):
        """3 concurrent requests < max_batch: ONE batch dispatched at the
        timeout, padded to the bucket (4), every response bitwise-equal
        to its direct single-request run."""
        eng = ServingEngine(exported_mlp, max_batch_size=8,
                            batch_timeout_ms=20, buckets="1,2,4,8x4")
        with eng:
            samples = [_sample(i) for i in range(3)]
            futs = [eng.submit([s]) for s in samples]
            outs = [f.result(timeout=10) for f in futs]
        pred = inference.create_predictor(inference.Config(exported_mlp))
        for s, (o,) in zip(samples, outs):
            direct, = pred.run([s[None]])
            np.testing.assert_array_equal(o, direct[0])
        snap = eng.metrics.snapshot()
        assert snap["batches"] == 1          # coalesced, not 3 singles
        assert snap["mean_batch_size"] == 3.0
        assert snap["padding_waste_ratio"] == pytest.approx(0.25)  # 1/4

    def test_full_batch_flushes_without_waiting(self, exported_mlp):
        """max_batch requests flush immediately (well before a long
        timeout)."""
        eng = ServingEngine(exported_mlp, max_batch_size=4,
                            batch_timeout_ms=5_000, buckets="1,2,4x4")
        with eng:
            t0 = time.monotonic()
            futs = [eng.submit([_sample(i)]) for i in range(4)]
            for f in futs:
                f.result(timeout=10)
            elapsed = time.monotonic() - t0
        assert elapsed < 2.0  # nowhere near the 5s timeout
        assert eng.metrics.snapshot()["mean_batch_size"] == 4.0

    def test_multi_bucket_bitwise_parity(self, exported_mlp):
        """E2E acceptance: concurrent requests across ≥2 shape buckets
        (seq 4 and seq 8) return responses bitwise-identical to direct
        Predictor.run."""
        eng = ServingEngine(exported_mlp, batch_timeout_ms=2,
                            buckets="1,2,4x4,8")
        pred = inference.create_predictor(inference.Config(exported_mlp))
        with eng:
            cases = [(i, _sample(i, seq=4 if i % 2 else 8))
                     for i in range(12)]
            results = {}

            def fire(i, s):
                results[i] = eng.predict([s], timeout=10)

            threads = [threading.Thread(target=fire, args=c) for c in cases]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert len(results) == 12
        for i, s in cases:
            direct, = pred.run([s[None]])
            np.testing.assert_array_equal(results[i][0], direct[0])

    def test_seq_padding_unpads_to_original_length(self, exported_mlp):
        """A seq-3 request padded into the seq-4 bucket comes back
        sliced to 3 tokens, bitwise-equal to its unpadded direct run."""
        eng = ServingEngine(exported_mlp, batch_timeout_ms=2,
                            buckets="1,2x4")
        with eng:
            s = _sample(0, seq=3)
            out, = eng.predict([s], timeout=10)
        assert out.shape == (3, 3)
        pred = inference.create_predictor(inference.Config(exported_mlp))
        direct, = pred.run([s[None]])
        np.testing.assert_array_equal(out, direct[0])
        assert eng.metrics.snapshot()["padding_waste_ratio"] > 0

    def test_oversized_seq_rejected_at_submit(self, exported_mlp):
        eng = ServingEngine(exported_mlp, buckets="1,2x4")
        with eng:
            with pytest.raises(ValueError, match="exceeds"):
                eng.submit([_sample(0, seq=5)])

    def test_fixed_seq_export_only_pads_to_that_dim(self):
        """With a FIXED export seq dim, only requests whose bucket IS
        that dim are admitted — a request landing in any other bucket
        would be a shape the artifact cannot serve (and warmup never
        compiled), so it must fail at submit, not dispatch."""
        class Echo:
            def run(self, arrays):
                return [np.asarray(arrays[0])]

        eng = ServingEngine(Echo(), batch_timeout_ms=1, buckets="1,2x4,8",
                            input_specs=[((-1, 8, 2), "float32")])
        with eng:
            with pytest.raises(ValueError, match="dim 0"):
                eng.submit([np.zeros((3, 2), np.float32)])  # bucket 4 != 8
            out, = eng.predict([np.zeros((5, 2), np.float32)], timeout=10)
            assert out.shape == (5, 2)  # padded to 8, sliced back to 5


class _BlockingRunner:
    """Duck-typed predictor whose run() blocks until released — makes
    queue-pressure and deadline timing deterministic."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def run(self, arrays):
        self.calls += 1
        assert self.release.wait(30)
        return [np.asarray(arrays[0]) * 2.0]


class TestBackpressureDeadlinesCancellation:
    def _engine(self, runner, **kw):
        return ServingEngine(runner, max_batch_size=1, batch_timeout_ms=0,
                             buckets="1", **kw)

    def _start_blocked(self, eng, runner):
        fut = eng.submit([np.ones(2, np.float32)])
        deadline = time.monotonic() + 10
        while runner.calls == 0:  # batcher now blocked inside run()
            assert time.monotonic() < deadline
            time.sleep(0.001)
        return fut

    def test_queue_full_backpressure(self):
        runner = _BlockingRunner()
        eng = self._engine(runner, queue_depth=2)
        with eng:
            first = self._start_blocked(eng, runner)
            ok = [eng.submit([np.ones(2, np.float32)]) for _ in range(2)]
            with pytest.raises(QueueFullError):
                eng.submit([np.ones(2, np.float32)])
            assert eng.metrics.counters["rejected_queue_full"] == 1
            runner.release.set()
            for f in [first] + ok:
                np.testing.assert_array_equal(
                    f.result(timeout=10)[0], np.full(2, 2.0, np.float32))

    def test_deadline_expires_while_queued(self):
        runner = _BlockingRunner()
        eng = self._engine(runner, queue_depth=8)
        with eng:
            first = self._start_blocked(eng, runner)
            doomed = eng.submit([np.ones(2, np.float32)], deadline_ms=30)
            time.sleep(0.08)          # deadline passes while blocked
            runner.release.set()
            first.result(timeout=10)
            with pytest.raises(DeadlineExceededError):
                doomed.result(timeout=10)
            assert eng.metrics.counters["deadline_expired"] == 1

    def test_cancelled_request_never_runs(self):
        runner = _BlockingRunner()
        eng = self._engine(runner, queue_depth=8)
        with eng:
            first = self._start_blocked(eng, runner)
            victim = eng.submit([np.ones(2, np.float32)])
            assert victim.cancel()
            runner.release.set()
            first.result(timeout=10)
            eng.drain(timeout=10)
            assert victim.cancelled()
        assert runner.calls == 1      # the cancelled request cost no batch
        assert eng.metrics.counters["cancelled"] == 1

    def test_cancelled_then_expired_request_does_not_kill_batcher(self):
        """A request that is BOTH cancelled and deadline-expired must be
        dropped by the sweep, not set_exception'd (InvalidStateError
        would kill the batcher thread)."""
        runner = _BlockingRunner()
        eng = self._engine(runner, queue_depth=8)
        with eng:
            first = self._start_blocked(eng, runner)
            victim = eng.submit([np.ones(2, np.float32)], deadline_ms=10)
            assert victim.cancel()
            time.sleep(0.05)          # deadline long past when swept
            runner.release.set()
            first.result(timeout=10)
            out, = eng.predict([np.ones(2, np.float32)], timeout=10)
            np.testing.assert_array_equal(out, np.full(2, 2.0, np.float32))

    def test_batchless_output_fails_batch_not_engine(self):
        """A model output missing the batch dim fails that batch's
        futures — the batcher survives and keeps draining."""
        class NoBatchDim:
            def run(self, arrays):
                return [np.float32(1.0)]

        eng = self._engine(NoBatchDim(), queue_depth=8)
        with eng:
            with pytest.raises(Exception):
                eng.predict([np.ones(2, np.float32)], timeout=10)
            assert eng.drain(timeout=10)   # batcher alive to finish
        assert eng.metrics.counters["errors"] == 1

    def test_shape_signature_cap_without_specs(self):
        """No input specs = no shape validation — the max_buckets cap is
        what stops shape-cycling traffic from forcing one compile per
        request (each cached forever)."""
        class Echo:
            def run(self, arrays):
                return [np.asarray(arrays[0]) * 2.0]

        eng = ServingEngine(Echo(), max_batch_size=1, batch_timeout_ms=0,
                            buckets="1", queue_depth=8, max_buckets=2)
        with eng:
            eng.predict([np.ones(2, np.float32)], timeout=10)
            eng.predict([np.ones(3, np.float32)], timeout=10)
            with pytest.raises(ValueError, match="max_buckets"):
                eng.submit([np.ones(4, np.float32)])
            # known signatures still served after the cap trips
            out, = eng.predict([np.ones(2, np.float32)], timeout=10)
            np.testing.assert_array_equal(out, np.full(2, 2.0, np.float32))

    def test_submit_after_drain_rejected(self):
        runner = _BlockingRunner()
        runner.release.set()
        eng = self._engine(runner, queue_depth=8)
        with eng:
            eng.predict([np.ones(2, np.float32)], timeout=10)
            assert eng.drain(timeout=10)
            with pytest.raises(EngineStoppedError):
                eng.submit([np.ones(2, np.float32)])

    def test_batch_error_fails_those_futures_not_the_engine(self):
        class Exploding:
            calls = 0

            def run(self, arrays):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("boom")
                return [np.asarray(arrays[0]) * 2.0]

        eng = self._engine(Exploding(), queue_depth=8)
        with eng:
            with pytest.raises(RuntimeError, match="boom"):
                eng.predict([np.ones(2, np.float32)], timeout=10)
            # engine survives and serves the next request
            out, = eng.predict([np.ones(2, np.float32)], timeout=10)
            np.testing.assert_array_equal(out, np.full(2, 2.0, np.float32))
        assert eng.metrics.counters["errors"] == 1


class _CompileTripwire:
    """Fails the test on ANY XLA compilation while armed — the serving
    analog of test_train_engine's sync tripwires."""

    def __enter__(self):
        import jax._src.compiler as C

        self._mod = C
        self._orig = C.compile_or_get_cached

        def hook(*a, **k):
            raise AssertionError(
                "XLA compilation after serving warmup — the bucket cache "
                "missed (recompile storm)")

        C.compile_or_get_cached = hook
        return self

    def __exit__(self, *exc):
        self._mod.compile_or_get_cached = self._orig
        return False


class TestZeroRecompileAfterWarmup:
    def test_steady_state_never_compiles(self, exported_mlp):
        """Warm every (batch × seq) bucket, then serve concurrent mixed
        traffic with jax's compile entry point booby-trapped: any
        compilation fails the test.  Responses stay bitwise-correct."""
        pred = inference.create_predictor(inference.Config(exported_mlp))
        eng = ServingEngine(pred, batch_timeout_ms=2, buckets="1,2,4x4,8")
        # oracle outputs (and their batch-1 buckets) computed BEFORE
        # arming the tripwire
        cases = [(i, _sample(i, seq=4 + 4 * (i % 2))) for i in range(16)]
        oracle = {i: pred.run([s[None]])[0][0] for i, s in cases}
        eng.start()
        warmed = pred.compile_count
        assert warmed >= 6  # 3 batch × 2 seq buckets (+ oracle shapes)
        with _CompileTripwire():
            results = {}

            def fire(i, s):
                results[i] = eng.predict([s], timeout=30)

            threads = [threading.Thread(target=fire, args=c) for c in cases]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert eng.drain(timeout=30)
        assert pred.compile_count == warmed
        assert eng.metrics.snapshot()["compile_count"] == warmed
        for i, s in cases:
            np.testing.assert_array_equal(results[i][0], oracle[i])

    def test_tripwire_catches_real_compile(self):
        """Meta-test: the tripwire actually fires on a fresh compile."""
        import jax
        import jax.numpy as jnp

        with _CompileTripwire():
            with pytest.raises(AssertionError, match="recompile"):
                jax.jit(lambda x: x * 3 + 1)(jnp.arange(7.0))


class TestHTTPServer:
    @pytest.fixture()
    def server(self, exported_mlp):
        eng = ServingEngine(exported_mlp, batch_timeout_ms=2,
                            buckets="1,2,4x4")
        srv = ServingServer(eng, port=0,
                            install_signal_handlers=False).start()
        yield srv
        srv.shutdown()

    def test_predict_healthz_metrics(self, server, exported_mlp):
        client = ServingClient(server.url)
        h = client.healthz()
        assert h["status_code"] == 200 and h["status"] == "ok"
        # enriched identity fields (PR 12): fleet sweeps compare these
        # to detect version skew
        assert h["pid"] > 0 and h["device_count"] >= 1
        assert "version" in h and "jax_version" in h
        assert h["uptime_s"] >= 0.0
        s = _sample(3)
        out, = client.predict([s])
        pred = inference.create_predictor(inference.Config(exported_mlp))
        direct, = pred.run([s[None]])
        np.testing.assert_array_equal(out, direct[0])
        text = client.metrics()
        for needle in ("paddle_serving_qps", "paddle_serving_p99_ms",
                       "paddle_serving_p50_ms",
                       "paddle_serving_padding_waste_ratio",
                       "paddle_serving_batch_size_bucket",
                       "paddle_serving_queue_latency_ms_bucket"):
            assert needle in text, needle

    def test_bad_requests(self, server):
        client = ServingClient(server.url)
        # raw bodies straight to the server (bypassing client-side
        # validation): ragged input, missing key, unknown route
        status, _ = client._request("/predict",
                                    {"inputs": [[[1.0], [1.0, 2.0]]]})
        assert status == 400
        status, _ = client._request("/predict", {"not_inputs": 1})
        assert status == 400
        # wrong rank vs the export manifest: rejected at submit, not a
        # 500 out of XLA
        status, _ = client._request("/predict", {"inputs": [[1.0, 2.0]]})
        assert status == 400
        status, _ = client._request("/nope")
        assert status == 404


class TestSigtermDrain:
    def test_chaos_preemption_drains_clean(self, exported_mlp):
        """E2E acceptance: chaos.inject self-preemption (SIGTERM from the
        batcher thread, latched by the resilience guard) → server drains
        — every accepted request completes, new work is rejected, wait()
        returns 0."""
        # max bucket 8 + a 60ms flush window: all 8 requests (across TWO
        # seq buckets) are accepted before the first dispatch fires the
        # injected self-SIGTERM, so every one of them is in-flight when
        # the drain starts — the drain must complete them all
        eng = ServingEngine(exported_mlp, batch_timeout_ms=60,
                            buckets="1,2,4,8x4,8")
        srv = ServingServer(eng, port=0).start()  # installs the latch
        client = ServingClient(srv.url)
        samples = {i: _sample(i, seq=4 if i % 2 else 8) for i in range(8)}
        results, errors = [], []

        def fire(i):
            try:
                results.append((i, client.predict([samples[i]])))
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        with chaos.inject(preempt_at_step=1):
            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert srv.wait(timeout=30) == 0  # clean drain exit
        assert chaos.active_config().fired == []  # inject popped
        assert not errors, errors
        assert len(results) == 8
        pred = inference.create_predictor(inference.Config(exported_mlp))
        for i, (out,) in results:
            direct, = pred.run([samples[i][None]])
            np.testing.assert_array_equal(out, direct[0])
        # engine rejects post-drain work; the listener is closed
        with pytest.raises(EngineStoppedError):
            eng.submit([_sample(0)])
        with pytest.raises(Exception):
            client.healthz()

    def test_programmatic_shutdown_is_clean(self, exported_mlp):
        eng = ServingEngine(exported_mlp, batch_timeout_ms=2, buckets="1x4")
        srv = ServingServer(eng, port=0,
                            install_signal_handlers=False).start()
        ServingClient(srv.url).predict([_sample(1)])
        assert srv.shutdown() is True
        assert srv.wait(timeout=5) == 0
        assert srv.shutdown() is True  # idempotent


class TestModelServe:
    def test_model_serve_roundtrip(self):
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(16, 3))
        model = paddle.Model(net)
        srv = model.serve(
            port=0, blocking=False, install_signal_handlers=False,
            input_spec=[paddle.static.InputSpec([-1, 8], "float32")],
            max_batch_size=4, batch_timeout_ms=2)
        try:
            x = np.random.RandomState(0).randn(8).astype(np.float32)
            out, = ServingClient(srv.url).predict([x])
            ref = np.asarray(model.predict_batch(
                [paddle.to_tensor(x[None])]).numpy())[0]
            np.testing.assert_array_equal(out, ref)
            assert srv.engine._predictor.compile_count >= 3  # warmed
        finally:
            srv.shutdown()
