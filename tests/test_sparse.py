"""paddle_tpu.sparse — the recommender workload's contracts.

Pins, on the conftest's 8 virtual CPU devices:

  * lookup/scatter-add numerics — the custom-VJP gather matches a dense
    one-hot oracle BITWISE unsharded; across mesh geometries (dp8,
    dp2×fsdp2×tp2, fsdp4×tp2) the sharded grads agree to float32 ULP;
    repeated ids accumulate exactly (the dedup must not change sums);
  * vocab admission — threshold/OOV/eviction behave deterministically:
    the same stream always yields the same id→row mapping, and the
    mapping round-trips through state_dict JSON;
  * fit integration — a wide-ish model trains through Model.fit(layout=)
    with the table row-sharded (per-device shard < full table), and the
    table + vocab state survive a checkpoint save → elastic restore
    ACROSS an axis-geometry change;
  * streaming — the click-log pipeline is seeded-reproducible and pads
    to the configured buckets only;
  * serving — bucket-warmed sharded lookup answers a steady-state burst
    with ZERO new compiles (the tripwire the AOT warmup exists for).

Run standalone via tools/sparse_smoke.sh.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse
from paddle_tpu.distributed.layout import SpecLayout
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.hapi import Model

pytestmark = pytest.mark.sparse

needs8 = pytest.mark.skipif(jax.device_count() < 8,
                            reason="needs the 8-virtual-device conftest mesh")


def _oracle_grad(table, ids, cot):
    """Dense one-hot scatter-add oracle: d(table) for out = table[ids]."""
    onehot = jax.nn.one_hot(ids.reshape(-1), table.shape[0],
                            dtype=table.dtype)
    return onehot.T @ cot.reshape(-1, table.shape[1])


# -- lookup / scatter-add numerics -----------------------------------------
class TestLookupParity:
    def test_forward_matches_oracle_bitwise(self):
        rs = np.random.RandomState(0)
        table = rs.randn(32, 8).astype(np.float32)
        ids = rs.randint(0, 32, (4, 6))
        out = sparse.embedding_lookup(jnp.asarray(table), jnp.asarray(ids))
        np.testing.assert_array_equal(np.asarray(out), table[ids])

    def test_grad_matches_oracle(self):
        rs = np.random.RandomState(1)
        table = jnp.asarray(rs.randn(32, 8).astype(np.float32))
        ids = jnp.asarray(rs.randint(0, 32, (4, 6)))
        cot = jnp.asarray(rs.randn(4, 6, 8).astype(np.float32))

        g = jax.grad(
            lambda t: (sparse.embedding_lookup(t, ids) * cot).sum())(table)
        ref = _oracle_grad(table, ids, cot)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_repeated_ids_accumulate(self):
        """A hot id repeated k times gets the SUM of its k cotangent
        rows — dedup merges, it must not drop or average."""
        table = jnp.zeros((8, 4), jnp.float32)
        ids = jnp.asarray([3, 3, 3, 5])
        cot = jnp.asarray(np.arange(16, dtype=np.float32).reshape(4, 4))
        g = jax.grad(
            lambda t: (sparse.embedding_lookup(t, ids) * cot).sum())(table)
        g = np.asarray(g)
        np.testing.assert_array_equal(
            g[3], np.asarray(cot[:3]).sum(0))
        np.testing.assert_array_equal(g[5], np.asarray(cot[3]))
        assert np.all(g[[0, 1, 2, 4, 6, 7]] == 0)

    def test_grad_inside_donated_jitted_step(self):
        """The scatter-add composes with jit + donation — the engine's
        one-step contract (no host round-trip in the grad path)."""
        rs = np.random.RandomState(2)
        table = jnp.asarray(rs.randn(16, 4).astype(np.float32))
        ids = jnp.asarray(rs.randint(0, 16, (8,)))

        @lambda f: jax.jit(f, donate_argnums=(0,))
        def step(t):
            return t - 0.1 * jax.grad(
                lambda tt: (sparse.embedding_lookup(tt, ids) ** 2).sum())(t)

        ref = np.asarray(table) - 0.1 * np.asarray(_oracle_grad(
            table, ids, 2.0 * jnp.take(table, ids, axis=0)))
        got = np.asarray(step(table))
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)

    @needs8
    @pytest.mark.parametrize("axes", [{"dp": 8}, {"dp": 2, "fsdp": 2,
                                                  "tp": 2},
                                      {"fsdp": 4, "tp": 2}])
    def test_sharded_grad_matches_unsharded_to_ulp(self, axes):
        rs = np.random.RandomState(3)
        table = rs.randn(64, 8).astype(np.float32)
        ids = rs.randint(0, 64, (32,))
        cot = rs.randn(32, 8).astype(np.float32)

        def g_fn(t, i, c):
            return jax.grad(
                lambda tt: (sparse.embedding_lookup(tt, i) * c).sum())(t)

        ref = np.asarray(jax.jit(g_fn)(table, ids, cot))

        mesh = build_mesh(axes)
        spec = sparse.table_spec()
        kept = P(tuple(a for a in spec[0] if a in mesh.axis_names) or None,
                 None)
        t_sh = jax.device_put(table, NamedSharding(mesh, kept))
        got = np.asarray(jax.jit(g_fn)(t_sh, ids, cot))
        # sharding relocates the math; the scatter segments reassociate
        # at most once per shard boundary → ULP-scale agreement
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


# -- vocab admission -------------------------------------------------------
class TestVocabAdmission:
    def test_threshold_and_oov(self):
        v = sparse.VocabAdmission(capacity=8, threshold=2)
        r1 = v.map_ids(np.array([10, 11, 10]))
        # id 10 seen twice -> admitted; 11 once -> OOV
        assert r1[0] == r1[2] != sparse.OOV_ROW
        assert r1[1] == sparse.OOV_ROW
        r2 = v.map_ids(np.array([11]))
        assert r2[0] != sparse.OOV_ROW  # second sighting crosses threshold

    def test_capacity_exhaustion_routes_to_oov(self):
        v = sparse.VocabAdmission(capacity=3, threshold=1)
        rows = v.map_ids(np.arange(100, 110))
        assert v.free_rows == 0
        assert (rows == sparse.OOV_ROW).sum() == 8  # 2 dedicated rows

    def test_determinism_across_instances(self):
        rs = np.random.RandomState(4)
        stream = [rs.zipf(1.5, size=32) % 1000 for _ in range(20)]
        va = sparse.VocabAdmission(capacity=64, threshold=2, seed=7)
        vb = sparse.VocabAdmission(capacity=64, threshold=2, seed=7)
        for batch in stream:
            np.testing.assert_array_equal(va.map_ids(batch),
                                          vb.map_ids(batch))

    def test_eviction_recycles_cold_rows(self):
        v = sparse.VocabAdmission(capacity=4, threshold=1, evict_after=2)
        v.map_ids(np.array([1, 2, 3]))       # rows fill (capacity-1 = 3)
        assert v.free_rows == 0
        v.map_ids(np.array([1]))
        v.map_ids(np.array([1]))
        v.map_ids(np.array([1]))             # 2,3 now cold (3 batches)
        cold = v.evict()
        assert len(cold) == 2 and v.free_rows == 2
        # recycled rows are reassigned to new hot ids
        r = v.map_ids(np.array([99]))
        assert r[0] in cold

    def test_state_dict_json_round_trip(self):
        v = sparse.VocabAdmission(capacity=16, threshold=1, evict_after=3)
        for i in range(5):
            v.map_ids(np.arange(i, i + 6))
        blob = json.dumps(v.state_dict())   # manifest-meta contract
        w = sparse.VocabAdmission(capacity=16, threshold=1)
        w.load_state_dict(json.loads(blob))
        probe = np.arange(0, 12)
        np.testing.assert_array_equal(w.lookup_rows(probe),
                                      v.lookup_rows(probe))
        # and the sketch state carried over: admission continues, not
        # restarts — the next batch maps identically in both
        np.testing.assert_array_equal(w.map_ids(probe), v.map_ids(probe))

    def test_capacity_mismatch_rejected(self):
        v = sparse.VocabAdmission(capacity=16)
        w = sparse.VocabAdmission(capacity=8)
        with pytest.raises(ValueError, match="capacity"):
            w.load_state_dict(v.state_dict())


# -- streaming pipeline ----------------------------------------------------
class TestStream:
    def test_seeded_reproducibility(self):
        mk = lambda: sparse.make_stream_loader(  # noqa: E731
            sparse.synthetic_click_log(200, seed=11), batch_size=16,
            buckets=(4, 8, 16))
        a = [tuple(np.asarray(x).tobytes() for x in b) for b in mk()]
        b = [tuple(np.asarray(x).tobytes() for x in b) for b in mk()]
        assert a and a == b

    def test_pads_to_buckets_only(self):
        loader = sparse.make_stream_loader(
            sparse.synthetic_click_log(300, seed=5), batch_size=8,
            buckets=(4, 8))
        widths = {np.asarray(b[1]).shape[1] for b in loader}
        assert widths <= {4, 8}

    def test_lengths_and_truncation(self):
        samples = [(1, list(range(20)), 1.0), (2, [7], 0.0)]
        users, items, lens, labels = sparse.ragged_collate(
            samples, buckets=(4, 8))
        assert items.shape == (2, 8)
        assert list(lens) == [8, 1]          # 20 truncated to cap, tail kept
        np.testing.assert_array_equal(items[0], np.arange(12, 20))
        assert labels.shape == (2, 1)

    def test_admission_stats_flow_to_registry(self):
        from paddle_tpu.utils.metrics import default_registry
        reg = default_registry()
        before = reg.counter("paddle_sparse_oov_total").value
        v = sparse.VocabAdmission(capacity=4, threshold=10**9)  # admit none
        loader = sparse.make_stream_loader(
            sparse.synthetic_click_log(64, seed=3), batch_size=16,
            item_vocab=v)
        batches = list(loader)
        assert batches
        assert all((np.asarray(b[1]) == sparse.OOV_ROW).all()
                   for b in batches)
        assert reg.counter("paddle_sparse_oov_total").value > before


# -- Model.fit integration + elastic checkpoint ----------------------------
def _wide_model(rows=256, dim=8, vocab=None, lr=0.05):
    paddle.seed(0)

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = sparse.ShardedEmbeddingTable(rows, dim, vocab=vocab)
            self.head = paddle.nn.Linear(dim, 1)

        def forward(self, users, items, lens):
            from paddle_tpu.tensor import apply

            ie = self.emb(items)

            def pool(e, n):
                m = (jnp.arange(e.shape[1])[None, :]
                     < n[:, None]).astype(e.dtype)
                return (e * m[..., None]).sum(1) / jnp.maximum(
                    n.astype(e.dtype), 1.0)[:, None]

            return self.head(apply(pool, ie, lens))

    net = Net()
    model = Model(net)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=lr,
                              parameters=net.parameters()),
        paddle.nn.BCEWithLogitsLoss())
    return model


class _Probe(paddle.callbacks.Callback):
    """Collect finite per-step losses + one table-shard measurement
    WHILE the engine is live (fit de-shards state on exit)."""

    def __init__(self, table_shape):
        super().__init__()
        self._shape = tuple(table_shape)
        self.losses = []
        self.shard_info = {}

    def on_train_batch_end(self, step, logs=None):
        v = (logs or {}).get("loss")
        if v is not None and np.isfinite(np.asarray(v)):
            self.losses.append(float(np.asarray(v)))
        eng = getattr(self.model, "_engine", None)
        if not self.shard_info and eng is not None \
                and eng.state is not None:
            for arr in jax.tree_util.tree_leaves(eng.state["trainable"]):
                if tuple(getattr(arr, "shape", ())) == self._shape:
                    self.shard_info = {
                        "full": int(arr.nbytes),
                        "shard": max(int(s.data.nbytes)
                                     for s in arr.addressable_shards)}


@needs8
class TestFitIntegration:
    def test_layout_shards_table_and_loss_decreases(self):
        vocab = sparse.VocabAdmission(capacity=256, threshold=1)
        model = _wide_model(vocab=vocab)
        loader = sparse.make_stream_loader(
            sparse.synthetic_click_log(2000, seed=1), batch_size=32,
            item_vocab=vocab, buckets=(4, 8, 16))
        mesh = build_mesh({"dp": 2, "fsdp": 2, "tp": 2})

        probe = _Probe((256, 8))
        model.fit(loader, epochs=1, num_iters=40, verbose=0,
                  mesh=mesh, layout=SpecLayout(), callbacks=[probe])
        # row-sharded over fsdp2×tp2 → 4 shards, each a quarter
        assert probe.shard_info["shard"] * 4 == probe.shard_info["full"]
        losses = probe.losses
        assert len(losses) >= 20
        assert np.mean(losses[-10:]) < np.mean(losses[:10])

    def test_ckpt_roundtrip_across_geometry_change(self, tmp_path):
        """Save on dp2×fsdp2×tp2, restore on dp8: the table re-lands on
        the new mesh AND the vocab id→row mapping rides the manifest —
        post-resume lookups hit the rows the restored table trained."""
        def make_loader():
            # EXACTLY 10 batches: the vocab state at the step-10 save is
            # the stream-end state (prefetch cannot run ahead of it)
            return sparse.make_stream_loader(
                sparse.synthetic_click_log(320, seed=2), batch_size=32,
                item_vocab=vocab_box[0], buckets=(8,))

        va = sparse.VocabAdmission(capacity=256, threshold=1)
        vocab_box = [va]
        ma = _wide_model(vocab=va)
        ma.fit(make_loader(), epochs=1, num_iters=10, verbose=0,
               mesh=build_mesh({"dp": 2, "fsdp": 2, "tp": 2}),
               layout=SpecLayout(), resume=str(tmp_path),
               checkpoint_interval=5)
        ref_w = ma.network.emb.embedding.numpy()
        probe = np.arange(0, 500)
        ref_rows = va.lookup_rows(probe)
        assert va.assigned > 0

        vb = sparse.VocabAdmission(capacity=256, threshold=1)
        vocab_box[0] = vb
        mb = _wide_model(vocab=vb)
        # fresh-process stand-in: nothing trained, different mesh; resume
        # restores table bytes + vocab mapping from the checkpoint, then
        # fast-forwards the (identical) stream without re-training
        mb.fit(make_loader(), epochs=1, num_iters=10, verbose=0,
               mesh=build_mesh({"dp": 8}), layout=SpecLayout(),
               resume=str(tmp_path), checkpoint_interval=5)
        np.testing.assert_array_equal(mb.network.emb.embedding.numpy(),
                                      ref_w)
        # the replayed stream holds no unseen ids → the restored mapping
        # is stable through the fast-forward
        np.testing.assert_array_equal(vb.lookup_rows(probe), ref_rows)
        assert vb.assigned == va.assigned


# -- serving path ----------------------------------------------------------
@needs8
class TestServing:
    def test_zero_steady_state_compiles(self):
        rs = np.random.RandomState(0)
        table = rs.randn(64, 8).astype(np.float32)
        mesh = build_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        eng = sparse.lookup_engine(table, mesh=mesh, max_batch_size=4,
                                   id_buckets=(2, 4))
        with eng:
            c0 = eng.metrics.snapshot()["compile_count"]
            assert c0 > 0  # warmup really compiled the bucket grid
            for i in range(24):
                ids = rs.randint(0, 64, size=(i % 4) + 1)
                eng.predict([ids])
            snap = eng.metrics.snapshot()
            assert snap["compile_count"] == c0
            assert snap["responses"] == 24

    def test_pooled_lookup_matches_table(self):
        table = np.arange(32, dtype=np.float32).reshape(8, 4)
        pred = sparse.SparseLookupPredictor(table, pooled=True)
        (out,) = pred.run([np.array([[1, 3]], np.int32)])
        np.testing.assert_allclose(np.asarray(out)[0],
                                   table[[1, 3]].mean(0), rtol=1e-6)

    def test_vocab_translation_on_serve(self):
        """Raw ids route through the admission mapping read-only:
        admitted ids hit their row, unknown ids the OOV row."""
        v = sparse.VocabAdmission(capacity=8, threshold=1)
        v.map_ids(np.array([100]))
        row = int(v.lookup_rows(np.array([100]))[0])
        table = np.random.RandomState(1).randn(8, 4).astype(np.float32)
        pred = sparse.SparseLookupPredictor(table, vocab=v, pooled=True)
        (out,) = pred.run([np.array([[100]], np.int32)])
        np.testing.assert_allclose(np.asarray(out)[0], table[row],
                                   rtol=1e-6)
        (oov,) = pred.run([np.array([[12345]], np.int32)])
        np.testing.assert_allclose(np.asarray(oov)[0],
                                   table[sparse.OOV_ROW], rtol=1e-6)

    def test_lookup_latency_lands_in_registry(self):
        from paddle_tpu.utils.metrics import default_registry
        table = np.zeros((8, 4), np.float32)
        pred = sparse.SparseLookupPredictor(table)
        for _ in range(8):
            pred.run([np.zeros((2, 2), np.int32)])
        r = default_registry().reservoir("paddle_sparse_lookup_ms")
        assert r.quantile(0.99) >= r.quantile(0.5) >= 0.0
