"""Spatial-op parity vs torch: transposed conv (stride/padding/
output_padding/groups), grid_sample, affine_grid, unfold — the
geometry-sensitive ops where off-by-one conventions hide."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402

rs = np.random.RandomState(37)


def _cmp(pd_out, t_out, atol=1e-4):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.detach().numpy(), atol=atol,
                               rtol=1e-4)


@pytest.mark.parametrize("stride,padding,output_padding,groups", [
    (1, 0, 0, 1), (2, 1, 0, 1), (2, 1, 1, 1), (3, 2, 1, 1), (2, 0, 0, 2),
])
def test_conv2d_transpose_parity(stride, padding, output_padding, groups):
    cin, cout = 4, 6
    x = rs.randn(2, cin, 7, 8).astype(np.float32)
    w = rs.randn(cin, cout // groups, 3, 3).astype(np.float32)
    b = rs.randn(cout).astype(np.float32)
    got = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             paddle.to_tensor(b), stride=stride,
                             padding=padding,
                             output_padding=output_padding,
                             groups=groups)
    want = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                               torch.tensor(b), stride=stride,
                               padding=padding,
                               output_padding=output_padding,
                               groups=groups)
    _cmp(got, want)


@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
@pytest.mark.parametrize("pad", ["zeros", "border", "reflection"])
@pytest.mark.parametrize("align", [True, False])
def test_grid_sample_parity(mode, pad, align):
    x = rs.randn(2, 3, 6, 7).astype(np.float32)
    # grid reaching past [-1, 1] so padding modes actually engage
    grid = (rs.rand(2, 5, 4, 2).astype(np.float32) * 3 - 1.5)
    got = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                        mode=mode, padding_mode=pad, align_corners=align)
    want = tF.grid_sample(torch.tensor(x), torch.tensor(grid), mode=mode,
                          padding_mode=pad, align_corners=align)
    _cmp(got, want)


@pytest.mark.parametrize("align", [True, False])
def test_affine_grid_parity(align):
    theta = rs.randn(2, 2, 3).astype(np.float32) * 0.5
    got = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 6],
                        align_corners=align)
    want = tF.affine_grid(torch.tensor(theta), [2, 3, 5, 6],
                          align_corners=align)
    _cmp(got, want, atol=1e-5)


def test_unfold_parity():
    x = rs.randn(2, 3, 8, 9).astype(np.float32)
    got = F.unfold(paddle.to_tensor(x), kernel_sizes=3, strides=2,
                   paddings=1, dilations=1)
    want = tF.unfold(torch.tensor(x), kernel_size=3, stride=2, padding=1,
                     dilation=1)
    _cmp(got, want, atol=1e-6)
