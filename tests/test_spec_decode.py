"""Speculative decode, chunked prefill, and the fleet router
(serving/generation.py draft path, serving/router.py).

Three contracts under test.  SPECULATIVE DECODE must be invisible to
the stream: greedy output bitwise-identical to the non-speculative
engine (and the model's own generate loop) whatever the draft proposes
— acceptance only changes HOW FAST tokens come, never WHICH tokens —
including mid-decode admission, rejection-heavy drafts (the drafted KV
of rejected proposals is overwritten before any emitted query attends
it), and seeded sampling lanes riding the same executable.  CHUNKED
PREFILL must hold token parity with unchunked admission while never
starving armed decode lanes, and a cancel mid-chunk must return every
privately-written page to the pool (the occupancy tripwire).  The
ROUTER must bind page-aligned prefixes to replicas (prefix_hit),
fail over off dead replicas, treat 429 as backpressure (retry, no
health flap), and carry one trace across client → router → replica.

Run via tools/serve_smoke.sh (`pytest -m specdec`); also in tier-1.
"""
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import flags as _flags
from paddle_tpu.framework.transfer import host_fetch
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.generation import GenerationEngine

pytestmark = pytest.mark.specdec

SAMPLE_KW = dict(do_sample=True, temperature=0.8, top_k=5)


def _gpt(layers, seed, max_pos=128):
    paddle.seed(seed)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=211, hidden_size=48, num_layers=layers, num_heads=4,
        max_position_embeddings=max_pos, dropout=0.0, attn_dropout=0.0))
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _gpt(2, seed=0)


@pytest.fixture(scope="module")
def draft(model):
    """1-layer draft seeded from the target's own weights (embeddings +
    first block) — the standard deployment shape, agrees often."""
    d = _gpt(1, seed=0)
    sd, dsd = model.state_dict(), d.state_dict()
    d.set_state_dict({k: (sd[k] if k in sd
                          and tuple(sd[k].shape) == tuple(v.shape) else v)
                      for k, v in dsd.items()})
    return d


@pytest.fixture(scope="module")
def bad_draft():
    """Independently-initialized draft: proposals are mostly wrong, so
    nearly every iteration exercises the rejection path."""
    return _gpt(1, seed=99)


@pytest.fixture(scope="module")
def eng_plain(model):
    eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                           prompt_buckets="8,16").start()
    yield eng
    eng.stop()


@pytest.fixture(scope="module")
def eng_spec(model, draft):
    eng = GenerationEngine(model, max_slots=3, max_seq_len=40,
                           prompt_buckets="8,16", draft_model=draft,
                           spec_tokens=3).start()
    yield eng
    eng.stop()


def solo(model, prompt, max_new, **kw):
    ids = paddle.to_tensor(np.array([prompt], np.int32))
    out = model.generate(ids, max_new_tokens=max_new, **kw)
    return np.array(out.numpy())[0, len(prompt):].tolist()


PROMPTS = [list(range(3, 10)), [5, 9, 2], list(range(50, 62)),
           [7, 7, 7, 11, 2, 4]]


# ---------------------------------------------------------------------------
# speculative decode
# ---------------------------------------------------------------------------
class TestSpecParity:
    def test_greedy_bitwise_vs_nonspec(self, model, eng_plain, eng_spec):
        """The headline contract: same tokens, with and without the
        draft, on a full concurrent batch."""
        hp = [eng_plain.submit(p, 12, seed=i) for i, p in
              enumerate(PROMPTS)]
        hs = [eng_spec.submit(p, 12, seed=i) for i, p in
              enumerate(PROMPTS)]
        plain = [h.result(60) for h in hp]
        spec = [h.result(60) for h in hs]
        assert spec == plain
        assert spec[0] == solo(model, PROMPTS[0], 12)

    def test_mid_decode_admission(self, eng_plain, eng_spec):
        """A lane admitted while others are mid-speculation gets the
        same stream it would get alone."""
        def staggered(eng):
            hs = []
            for i, p in enumerate(PROMPTS):
                hs.append(eng.submit(p, 10, seed=i))
                time.sleep(0.03)   # land mid-iteration of the others
            return [h.result(60) for h in hs]
        assert staggered(eng_spec) == staggered(eng_plain)

    def test_sampling_matched_distribution(self, eng_plain, eng_spec):
        """Seeded sampling lanes ride the speculative executable with an
        unchanged PRNG chain: bitwise-equal streams, not just equal in
        distribution."""
        a = eng_plain.generate(PROMPTS[1], 12, timeout=60, seed=7,
                               **SAMPLE_KW)
        b = eng_spec.generate(PROMPTS[1], 12, timeout=60, seed=7,
                              **SAMPLE_KW)
        assert a == b

    def test_rejection_rollback(self, model, bad_draft):
        """A near-always-wrong draft: every iteration writes drafted KV
        for proposals the target then rejects.  Those pages are inside
        the slot's reservation and the next iteration's scatter
        overwrites them before any emitted query attends them — output
        must stay bitwise-correct across sequential slot reuse."""
        eng = GenerationEngine(model, max_slots=2, max_seq_len=40,
                               prompt_buckets="8,16",
                               draft_model=bad_draft, spec_tokens=3)
        eng.start()
        try:
            for i, p in enumerate(PROMPTS):
                assert eng.generate(p, 10, timeout=60) == \
                    solo(model, p, 10)
            snap = eng.metrics.snapshot()
            assert snap["spec_proposed"] > 0
            # mostly-rejected, never negative; strictly below a shared-
            # weight draft's ratio
            assert 0.0 <= snap["spec_accept_ratio"] < 0.9
        finally:
            eng.stop()

    def test_accept_ratio_counter(self, eng_spec):
        """The acceptance counters move and the PTA007-clean gauge is
        exposed on /metrics."""
        eng_spec.generate(PROMPTS[0], 12, timeout=60)
        snap = eng_spec.metrics.snapshot()
        assert snap["spec_proposed"] > 0
        assert 0.0 < snap["spec_accept_ratio"] <= 1.0
        text = eng_spec.metrics.prometheus_text()
        assert "paddle_genserve_spec_accept_ratio" in text
        assert "paddle_genserve_spec_proposed_total" in text


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def eng_chunk(model):
    eng = GenerationEngine(model, max_slots=3, max_seq_len=96,
                           prompt_buckets=(16, 64), page_size=4,
                           prefill_chunk=8).start()
    yield eng
    eng.stop()


class TestChunkedPrefill:
    def test_token_parity_vs_unchunked(self, model, eng_chunk):
        """A prompt sliced into 7 chunks decodes the same stream as the
        model's own one-shot generate."""
        rs = np.random.RandomState(3)
        for L in (40, 56, 23):
            p = [int(t) for t in rs.randint(1, 211, L)]
            assert eng_chunk.generate(p, 8, timeout=60) == \
                solo(model, p, 8)
        assert eng_chunk.metrics.snapshot()["prefill_chunks"] > 0

    def test_no_starvation_of_decode(self, eng_chunk):
        """The pin the chunking exists for: a short stream admitted
        BEFORE a long prompt keeps decoding one token per iteration
        while the long prompt's chunks interleave — it finishes before
        the long prompt emits its first token (4 decode iterations vs 7
        prefill chunks)."""
        short = eng_chunk.submit(list(range(2, 10)), 4)
        assert short.next_token(timeout=60) is not None  # admitted
        long_h = eng_chunk.submit([int(t) for t in
                                   np.random.RandomState(5)
                                   .randint(1, 211, 56)], 4)
        t_first_long = [None]

        def watch_long():
            if long_h.next_token(timeout=60) is not None:
                t_first_long[0] = time.monotonic()
            long_h.result(60)

        w = threading.Thread(target=watch_long)
        w.start()
        short.result(60)
        t_short_done = time.monotonic()
        w.join(60)
        assert t_first_long[0] is not None
        assert t_short_done < t_first_long[0], \
            "short stream stalled behind a long prefill"

    def test_cancel_mid_chunk_pool_tripwire(self, model):
        """Cancel a prompt halfway through its chunk schedule, repeat;
        every privately-written page must be back on the free stack
        (free_count returns to baseline — a leak here only surfaces in
        production as slow pool exhaustion)."""
        eng = GenerationEngine(model, max_slots=2, max_seq_len=96,
                               prompt_buckets=(64,), page_size=4,
                               prefill_chunk=8, prefix_cache=False)
        eng.start()
        try:
            with host_fetch():
                free0 = int(np.array(eng._state["free_count"]))
            for cycle in range(3):
                h = eng.submit(list(range(1, 57)), 4)
                time.sleep(0.04)          # a few chunks land
                h.cancel()
                h.result(60)
                # a full request through the same slots still works
                assert len(eng.generate(list(range(3, 59)), 3,
                                        timeout=60)) == 3
            deadline = time.monotonic() + 30
            while eng._sched.occupied and time.monotonic() < deadline:
                time.sleep(0.02)
            with host_fetch():
                free1 = int(np.array(eng._state["free_count"]))
            assert free1 == free0, f"page leak: {free0} -> {free1}"
            assert eng.metrics.snapshot()["prefill_chunks"] > 0
        finally:
            eng.stop()


class TestPrefixCachePressure:
    def test_distinct_prompts_do_not_starve_pool(self, model):
        """Regression: idle prefix-cache residents must be LRU-evicted
        when admission needs their pages.  A stream of DISTINCT prompts
        once parked one-reader prefixes over the whole pool —
        ``pages_available`` hit zero, nothing ever evicted (entry-count
        capacity never trips on a small pool), and the backlog head
        waited forever."""
        eng = GenerationEngine(model, max_slots=2, max_seq_len=24,
                               prompt_buckets=(8,), page_size=4,
                               num_pages=9)
        eng.start()
        try:
            rs = np.random.RandomState(3)
            prompts = [rs.randint(1, 200, 8).tolist() for _ in range(10)]
            handles = [eng.submit(p, 8) for p in prompts]
            for h in handles:
                h.result(120)          # raises on stall — the old bug
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# fleet router
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def fleet(model):
    """Two real replica servers + the router in front of them."""
    from paddle_tpu.serving.router import FleetRouter
    from paddle_tpu.serving.server import ServingServer

    servers = []
    for _ in range(2):
        eng = GenerationEngine(model, max_slots=2, max_seq_len=64,
                               prompt_buckets=(16,), page_size=4)
        servers.append(ServingServer(
            None, gen_engine=eng, port=0,
            install_signal_handlers=False).start())
    router = FleetRouter([s.url for s in servers], port=0, page_size=4,
                         probe_interval_s=0.1, dead_after=2,
                         install_signal_handlers=False).start()
    yield router, servers
    router.shutdown()
    for s in servers:
        s.shutdown()


PREFIX = list(range(1, 13))   # 12 tokens -> 2 shareable pages (ps=4)


class _Stub429(BaseHTTPRequestHandler):
    """A healthy replica at capacity: /healthz 200, /generate 429."""

    def do_GET(self):  # noqa: N802
        body = b'{"status": "ok"}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        body = b'{"error": "generation queue full"}'
        self.send_response(429)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102
        pass


class TestRouter:
    def test_prefix_affinity(self, fleet):
        """A shared-prefix burst sticks to one replica after the first
        request binds the prefix (so the replica-side prefix cache can
        actually hit)."""
        from paddle_tpu.serving.client import ServingClient

        router, _ = fleet
        c = ServingClient(router.url)
        for i in range(6):
            out = c.generate(PREFIX + [20 + i], max_new_tokens=3)
            assert len(out["tokens"]) == 3
        routed = router.metrics.snapshot()["routed"]
        hits = {k: v for k, v in routed.items()
                if k.endswith("|prefix_hit")}
        assert sum(hits.values()) >= 5, routed
        assert len(hits) == 1, f"prefix bounced between replicas: {routed}"

    def test_429_is_backpressure_not_death(self, fleet):
        """A replica answering 429 gets the request retried elsewhere
        and keeps its health: no failover flap under load."""
        from paddle_tpu.serving.client import ServingClient
        from paddle_tpu.serving.router import FleetRouter

        _, servers = fleet
        stub = ThreadingHTTPServer(("127.0.0.1", 0), _Stub429)
        threading.Thread(target=stub.serve_forever, daemon=True).start()
        stub_url = f"http://127.0.0.1:{stub.server_address[1]}"
        router = FleetRouter([stub_url, servers[0].url], port=0,
                             page_size=4, probe_interval_s=0.1,
                             dead_after=2,
                             install_signal_handlers=False).start()
        try:
            c = ServingClient(router.url)
            # both replicas idle -> least_loaded tie-break picks r0 (the
            # stub), which 429s; the router must retry on r1 and succeed
            out = c.generate(PREFIX + [50], max_new_tokens=3)
            assert len(out["tokens"]) == 3
            snap = router.metrics.snapshot()
            assert snap["backpressure"].get("r0") == 1, snap
            assert sum(v for k, v in snap["routed"].items()
                       if k.startswith("r1|")) == 1, snap
            time.sleep(0.3)   # several probe rounds
            assert router.replicas[0].alive, \
                "429 bumped the health-failure count"
            assert router.metrics.snapshot()["replicas_healthy"] == 2
        finally:
            router.shutdown()
            stub.shutdown()
            stub.server_close()

    def test_traceparent_continuity(self, fleet):
        """One trace across the hop: client root -> router.generate ->
        replica server.generate land in the same in-process span ring
        under the same trace id."""
        import paddle_tpu.monitor as monitor
        from paddle_tpu.monitor import tracing
        from paddle_tpu.serving.client import ServingClient

        router, _ = fleet
        old = _flags.flag("FLAGS_trace_sample_rate")
        _flags.set_flags({"FLAGS_trace_sample_rate": 1.0})
        monitor.reset()
        try:
            c = ServingClient(router.url)
            out = c.generate(PREFIX + [88], max_new_tokens=3)
            assert len(out["tokens"]) == 3
            assert c.last_traceparent is not None
            trace_id = c.last_traceparent.split("-")[1]
            want = {"client.generate", "router.generate",
                    "server.generate"}
            deadline = time.monotonic() + 5
            names = set()
            while time.monotonic() < deadline and not want <= names:
                # the router handler ends its span just AFTER the client
                # finishes reading the response body — poll briefly
                names = {s["name"] for s in tracing.default_tracer()
                         .spans(trace_id=trace_id)}
                time.sleep(0.02)
            assert want <= names, names
        finally:
            _flags.set_flags({"FLAGS_trace_sample_rate": old})
            monitor.reset()

    def test_metrics_federation(self, fleet):
        """One scrape shows router counters AND every replica's genserve
        gauges under its banner."""
        from paddle_tpu.serving.client import ServingClient

        router, _ = fleet
        text = ServingClient(router.url).metrics()
        assert "paddle_router_requests_total" in text
        assert "# replica=r0" in text and "# replica=r1" in text
        assert "paddle_genserve_decode_tokens_per_sec" in text

    def test_dead_replica_failover(self, fleet):
        """Kill the replica that owns the burst prefix: probes mark it
        dead, the next same-prefix request lands on the survivor as
        health_failover, and the affinity REBINDS (stickiness to a
        corpse would re-miss forever).  Runs last — it downs a
        replica."""
        from paddle_tpu.serving.client import ServingClient

        router, servers = fleet
        c = ServingClient(router.url)
        c.generate(PREFIX + [60], max_new_tokens=2)
        routed = router.metrics.snapshot()["routed"]
        owner = max((k for k in routed if "|prefix_hit" in k
                     or "|least_loaded" in k),
                    key=routed.get).split("|")[0]
        idx = int(owner[1:])
        servers[idx].shutdown()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if router.metrics.snapshot()["replicas_healthy"] == 1:
                break
            time.sleep(0.05)
        assert router.metrics.snapshot()["replicas_healthy"] == 1
        out = c.generate(PREFIX + [61], max_new_tokens=2)
        assert len(out["tokens"]) == 2
        snap = router.metrics.snapshot()
        assert any(k.endswith("|health_failover") for k in
                   snap["routed"]), snap
        # rebound: the NEXT same-prefix request is a prefix_hit on the
        # survivor, not another failover
        c.generate(PREFIX + [62], max_new_tokens=2)
        survivor = f"r{1 - idx}"
        assert router.metrics.snapshot()["routed"].get(
            f"{survivor}|prefix_hit", 0) >= 1
