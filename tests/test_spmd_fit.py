"""SPMD-sharded TrainEngine (hapi/engine.py mesh mode): Model.fit scales
to every chip on the mesh.

Pins the contracts the mesh-aware engine introduces on the 8 virtual CPU
devices the conftest forces:

  * dp scaling shape — ONE global jitted step; per-device compiled work
    constant as dp grows (XLA cost analysis), grad sync present as a dp
    all-reduce in the partitioned module (engine path, complementing
    test_dp_scaling.py's hand-rolled step);
  * numerics — a dp=1 mesh is BITWISE the unsharded engine; dp=8 agrees
    with dp=1 to float32 ULP (XLA reassociates batch reductions into
    partial sums + all-reduce, so cross-dp-degree equality is exact to
    the ULP, not bit-for-bit — the probe that pinned this is described
    in hapi/engine.py's module docstring);
  * donation under sharding — with NamedShardings attached the donated
    state is actually consumed (no silent donation fallback);
  * amp.auto_cast(bf16) composes with the partitioned step;
  * preemption-resume round-trips BITWISE at a fixed dp degree;
  * the data path (transfer.shard_batch + DataLoader.placement)
    pre-shards batches on the prefetch thread;
  * legacy DataParallel routes through the ambient mesh (deprecation).

Run standalone via tools/dp_smoke.sh.
"""
import warnings

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import amp
from paddle_tpu.distributed.mesh import (build_mesh, get_mesh, mesh_guard,
                                         parse_mesh_shape)
from paddle_tpu.framework.transfer import shard_batch
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.engine import TrainEngine, resolve_mesh
from paddle_tpu.io import DataLoader, TensorDataset

pytestmark = pytest.mark.dp

needs8 = pytest.mark.skipif(jax.device_count() < 8,
                            reason="needs the 8-virtual-device conftest mesh")


def _model_and_data(n=24, lr=0.01):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    ds = TensorDataset([x, y])
    model = Model(net)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=lr,
                              parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss())
    return model, ds


def _weights(model):
    return {k: np.asarray(p._value)
            for k, p in model.network.named_parameters()}


def _fit(mesh=None, epochs=2, **kw):
    model, ds = _model_and_data()
    hist = model.fit(ds, batch_size=8, epochs=epochs, shuffle=False,
                     verbose=0, log_freq=1, mesh=mesh, **kw)
    return model, hist


# -- parity ----------------------------------------------------------------
@needs8
class TestDpParity:
    def test_dp1_mesh_bitwise_matches_unsharded_engine(self):
        """The degenerate single-device mesh runs the partitioned
        pipeline but must not change a single bit vs the PR-2 engine."""
        m0, h0 = _fit(mesh=None)
        m1, h1 = _fit(mesh={"dp": 1})
        np.testing.assert_array_equal(h0["loss"], h1["loss"])
        w0, w1 = _weights(m0), _weights(m1)
        for k in w0:
            np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)

    @staticmethod
    def _per_step_losses(dp, steps=6, B=16):
        """Drive the engine directly: SAME global batch at both dp
        degrees, per-STEP losses off the ring."""
        paddle.seed(0)
        model, _ = _model_and_data()
        rs = np.random.RandomState(7)
        x = rs.randn(steps * B, 4).astype("float32")
        y = (x.sum(1) > 0).astype("int64")
        eng = TrainEngine(model).begin(mesh={"dp": dp})
        model.network.train()
        for i in range(steps):
            lo, hi = i * B, (i + 1) * B
            eng.step([paddle.to_tensor(x[lo:hi])],
                     [paddle.to_tensor(y[lo:hi])])
        losses = eng.drain()
        eng.finish()
        return losses, _weights(model)

    def test_dp8_per_step_losses_match_dp1_to_ulp(self):
        """Same global batch split over 8 devices: per-step losses agree
        with dp=1 to float32 ULP (the all-reduce reassociates the batch
        reductions; anything past ~1e-6 relative would mean a REAL
        divergence — wrong loss scaling, double-averaged grads...)."""
        la, wa = self._per_step_losses(1)
        lb, wb = self._per_step_losses(8)
        assert len(la) == len(lb) == 6
        np.testing.assert_allclose(la, lb, rtol=2e-6, atol=1e-7)
        for k in wa:
            np.testing.assert_allclose(wa[k], wb[k], rtol=1e-5,
                                       atol=1e-7, err_msg=k)

    def test_dp8_fit_loop_matches_dp1(self):
        """The same parity through the full fit() loop (loader
        placement, epoch means)."""
        ma, ha = _fit(mesh={"dp": 1})
        mb, hb = _fit(mesh={"dp": 8})
        np.testing.assert_allclose(ha["loss"], hb["loss"],
                                   rtol=2e-6, atol=1e-7)
        wa, wb = _weights(ma), _weights(mb)
        for k in wa:
            np.testing.assert_allclose(wa[k], wb[k], rtol=1e-5,
                                       atol=1e-7, err_msg=k)

    def test_global_batch_semantics(self):
        """batch_size is the GLOBAL batch: each device sees B/dp
        samples — the engine's input sharding splits dim 0 over dp."""
        model, ds = _model_and_data()
        eng = TrainEngine(model).begin(mesh={"dp": 8})
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))
        sx = shard_batch([x], eng.mesh)[0]
        assert sx._value.sharding.spec == P("dp")
        shard_shapes = {s.data.shape
                        for s in sx._value.addressable_shards}
        assert shard_shapes == {(2, 4)}
        eng.finish()


# -- scaling shape ---------------------------------------------------------
@needs8
class TestDpScalingShape:
    def _compiled(self, dp):
        model, ds = _model_and_data()
        eng = TrainEngine(model).begin(mesh={"dp": dp})
        rs = np.random.RandomState(0)
        B = 2 * dp
        x = paddle.to_tensor(rs.randn(B, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (B,)).astype("int64"))
        compiled = eng.lower_step([x], [y]).compile()
        eng.finish()
        return compiled

    @staticmethod
    def _flops(compiled):
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return float(ca.get("flops", 0.0))

    def test_constant_per_device_work_and_dp_all_reduce(self):
        """With per-device batch held constant the ENGINE's compiled
        step does constant per-device flops dp=1 -> dp=8 (XLA reports
        per-device numbers for SPMD modules) — the throughput model
        behind linear scaling.  The dp grad sync must exist as an
        all-reduce in the dp=8 module and must not exist at dp=1."""
        c1, c8 = self._compiled(1), self._compiled(8)
        f1, f8 = self._flops(c1), self._flops(c8)
        assert f1 > 0 and f8 > 0
        assert f8 / f1 < 1.15, (f1, f8)
        assert "all-reduce" in c8.as_text()
        assert "all-reduce" not in c1.as_text()


# -- donation --------------------------------------------------------------
@needs8
class TestDonationUnderSharding:
    def test_no_silent_donation_fallback(self):
        """With NamedShardings attached (in inferred from the committed
        state, out PINNED by the engine) XLA must still alias every
        state buffer: zero donation-fallback warnings, and every leaf of
        the pre-step state is consumed (deleted) by the dispatch."""
        model, ds = _model_and_data()
        eng = TrainEngine(model).begin(mesh={"dp": 8})
        refs = [v for tree in (eng.state["trainable"], eng.state["opt"],
                               eng.state["buffers"])
                for v in jax.tree_util.tree_leaves(tree)]
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))
        with warnings.catch_warnings():
            warnings.filterwarnings("error", message=".*donated buffers.*")
            eng.step([x], [y])
        undonated = [v for v in refs if not v.is_deleted()]
        assert not undonated, f"{len(undonated)} state buffers survived " \
                              "the donated dispatch (silent fallback)"
        assert eng.drain()
        eng.finish()

    def test_sharded_state_stays_layout_stable(self):
        """Pinned out_shardings: a second fit at the same placement
        reuses the cached jit (key = resolved sharding tree, so an
        identical-but-fresh rule doesn't retrace), while an annotation
        added between fits rebuilds it (stale pinned out_shardings
        would silently force the old layout)."""
        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
                  mesh={"dp": 8})
        eng = model._engine
        fn = eng._step_fn
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
                  mesh={"dp": 8}, sharding_rule=lambda n, p: None)
        assert eng._step_fn is fn  # same resolved shardings → cache hit
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
                  mesh={"dp": 8},
                  sharding_rule=lambda n, p: (P(None, "dp")
                                              if n == "0.weight" else None))
        assert eng._step_fn is not fn  # placement changed → rebuilt


# -- amp -------------------------------------------------------------------
@needs8
class TestAmpComposition:
    def test_auto_cast_bf16_inside_partitioned_step(self):
        """amp.auto_cast(bf16) at trace time must land INSIDE the
        partitioned computation (bf16 dots in the module) and train to
        finite losses on the dp=8 mesh."""
        model, ds = _model_and_data()
        with amp.auto_cast(enable=True, dtype="bfloat16"):
            hist = model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                             verbose=0, log_freq=1, mesh={"dp": 8})
        assert hist["loss"] and np.all(np.isfinite(hist["loss"]))
        # dtype policy honored inside the compiled partitioned step
        eng = model._engine
        eng.begin(mesh={"dp": 8})
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (16,)).astype("int64"))
        with amp.auto_cast(enable=True, dtype="bfloat16"):
            txt = eng.lower_step([x], [y]).as_text()
        eng.finish()
        assert "bf16" in txt

    def test_bf16_losses_track_fp32(self):
        ma, _ = _model_and_data()
        ha = ma.fit(_model_and_data()[1], batch_size=8, epochs=1,
                    shuffle=False, verbose=0, log_freq=1, mesh={"dp": 8})
        mb, _ = _model_and_data()
        with amp.auto_cast(enable=True, dtype="bfloat16"):
            hb = mb.fit(_model_and_data()[1], batch_size=8, epochs=1,
                        shuffle=False, verbose=0, log_freq=1,
                        mesh={"dp": 8})
        np.testing.assert_allclose(ha["loss"], hb["loss"], rtol=0.1)


# -- fault tolerance -------------------------------------------------------
@needs8
class TestShardedResume:
    def test_resume_bitwise_at_fixed_dp(self, tmp_path):
        """Checkpoint mid-fit on the dp=8 mesh (materialize gathers the
        sharded state to host), restore re-shards — bitwise vs the
        uninterrupted dp=8 run.  Same-dp resume has no reassociation
        anywhere, so this is exact."""
        ma, ds = _model_and_data(n=32)
        ma.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0,
               mesh={"dp": 8})
        ref = _weights(ma)

        mb, ds = _model_and_data(n=32)
        mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               mesh={"dp": 8}, resume=str(tmp_path), checkpoint_interval=3)
        mc, ds = _model_and_data(n=32)
        mc.fit(ds, batch_size=8, epochs=4, shuffle=False, verbose=0,
               mesh={"dp": 8}, resume=str(tmp_path), checkpoint_interval=3)
        got = _weights(mc)
        assert set(got) == set(ref)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    @pytest.mark.chaos
    def test_sigterm_preempt_resume_bitwise_under_sharding(self, tmp_path):
        """SIGTERM mid-fit on the mesh: emergency checkpoint from the
        sharded donated state, restart resumes to the same bits as a
        never-preempted dp=8 run."""
        from paddle_tpu.distributed.resilience import PREEMPTED_EXIT_CODE
        from paddle_tpu.utils import chaos

        ma, ds = _model_and_data(n=32)
        ma.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0,
               mesh={"dp": 8})
        ref = _weights(ma)

        mb, ds = _model_and_data(n=32)
        with chaos.inject(preempt_at_step=5):
            with pytest.raises(SystemExit) as ei:
                mb.fit(ds, batch_size=8, epochs=3, shuffle=False,
                       verbose=0, mesh={"dp": 8}, fault_tolerant=True,
                       resume=str(tmp_path))
        assert ei.value.code == PREEMPTED_EXIT_CODE
        chaos.reset()
        mc, ds = _model_and_data(n=32)
        mc.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0,
               mesh={"dp": 8}, resume=str(tmp_path))
        got = _weights(mc)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


# -- data path -------------------------------------------------------------
@needs8
class TestShardedDataPath:
    def test_shard_batch_splits_and_replicates(self):
        mesh = build_mesh({"dp": 8})
        rs = np.random.RandomState(0)
        batch = [paddle.to_tensor(rs.randn(16, 4).astype("float32")),
                 rs.randint(0, 2, (16,)).astype("int64"),
                 np.float32(3.0),          # scalar → replicated
                 rs.randn(13, 4).astype("float32")]  # 13 % 8 → replicated
        out = shard_batch(batch, mesh)
        assert out[0]._value.sharding.spec == P("dp")  # Tensor re-wrapped
        assert out[1].sharding.spec == P("dp")
        assert out[2].sharding.spec == P()
        assert out[3].sharding.spec == P()
        np.testing.assert_array_equal(np.asarray(out[0]._value),
                                      np.asarray(batch[0]._value))
        # idempotent: re-placing is a no-op, not a copy storm
        again = shard_batch(out, mesh)
        assert again[1] is out[1]

    def test_dataloader_placement_runs_on_prefetch_thread(self):
        """fit(mesh=) installs DataLoader.placement; batches arrive at
        the loop already dp-sharded, placed by the prefetch thread."""
        import threading

        mesh = build_mesh({"dp": 8})
        rs = np.random.RandomState(0)
        ds = TensorDataset([rs.randn(16, 4).astype("float32")])
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        seen_threads = []
        main = threading.get_ident()

        def placement(batch):
            seen_threads.append(threading.get_ident())
            return shard_batch(batch, mesh)

        loader.placement = placement
        batches = list(loader)
        assert len(batches) == 2
        for b in batches:
            assert b[0]._value.sharding.spec == P("dp")
        assert seen_threads and all(t != main for t in seen_threads)

    def test_fit_restores_placement_hook(self):
        model, ds = _model_and_data()
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        model.fit(loader, epochs=1, verbose=0, mesh={"dp": 8})
        assert loader.placement is None


# -- mesh resolution -------------------------------------------------------
class TestMeshResolution:
    def test_parse_mesh_shape(self):
        assert parse_mesh_shape("") is None
        assert parse_mesh_shape(None) is None
        assert parse_mesh_shape("dp=8") == {"dp": 8}
        assert parse_mesh_shape("dp:2,mp:4") == {"dp": 2, "mp": 4}
        assert parse_mesh_shape("dp") == {"dp": -1}
        assert parse_mesh_shape({"dp": 2}) == {"dp": 2}
        with pytest.raises(ValueError, match="dp=x8"):
            parse_mesh_shape("dp=x8")  # names the bad token
        with pytest.raises(ValueError, match="positive"):
            parse_mesh_shape("dp=0")

    @needs8
    def test_mesh_without_dp_axis_warns(self):
        """A typo'd axis name ('data=8') replicates the whole step on
        every device — that must warn, not silently burn 8× the
        chips."""
        model, ds = _model_and_data()
        with pytest.warns(UserWarning, match="no 'dp' axis"):
            model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                      verbose=0, mesh={"data": 8})

    @needs8
    def test_ambient_mesh_guard_is_picked_up(self):
        mesh = build_mesh({"dp": 8})
        with mesh_guard(mesh):
            model, hist = _fit(epochs=1)  # no mesh= argument
        assert model._engine.mesh is mesh
        assert np.all(np.isfinite(hist["loss"]))

    @needs8
    def test_flags_mesh_shape_is_picked_up(self):
        from paddle_tpu.framework import flags as F

        old = F.flag("FLAGS_mesh_shape")
        try:
            paddle.set_flags({"FLAGS_mesh_shape": "dp=8"})
            model, hist = _fit(epochs=1)
            assert model._engine.mesh is not None
            assert model._engine.mesh.shape["dp"] == 8
        finally:
            paddle.set_flags({"FLAGS_mesh_shape": old})

    @needs8
    def test_leftover_global_mesh_is_ignored(self):
        """set_mesh/ensure_mesh side effects (eager collectives set the
        global mesh) must NOT silently reshard a fit — only an ACTIVE
        mesh_guard scope counts as ambient."""
        from paddle_tpu.distributed.mesh import set_mesh

        prev = get_mesh()
        try:
            set_mesh(build_mesh({"dp": 8}))
            assert resolve_mesh(None) is None
            model, hist = _fit(epochs=1)
            assert model._engine.mesh is None
        finally:
            set_mesh(prev)

    @needs8
    def test_guard_scope_outranks_flag(self):
        """An EXPLICIT mesh_guard — even a deliberate 1-device one for
        debugging — must not be overridden by FLAGS_mesh_shape."""
        from paddle_tpu.framework import flags as F

        old = F.flag("FLAGS_mesh_shape")
        try:
            paddle.set_flags({"FLAGS_mesh_shape": "dp=8"})
            with mesh_guard(build_mesh({"dp": 1},
                                       devices=jax.devices()[:1])):
                assert resolve_mesh(None) is None
        finally:
            paddle.set_flags({"FLAGS_mesh_shape": old})

    def test_no_mesh_means_single_device_engine(self):
        # outside any mesh_guard scope resolution is None regardless of
        # leftover global-mesh state (see test_leftover_global_mesh_*)
        assert resolve_mesh(None) is None
        model, hist = _fit(epochs=1)
        assert model._engine.mesh is None

    @needs8
    def test_explicit_mesh_object(self):
        mesh = build_mesh({"dp": 4}, devices=jax.devices()[:4])
        model, hist = _fit(mesh=mesh, epochs=1)
        assert model._engine.mesh is mesh
        assert np.all(np.isfinite(hist["loss"]))


# -- per-param sharding rule (mp hook) -------------------------------------
@needs8
class TestShardingRule:
    def test_rule_shards_large_params_over_mp(self):
        """A per-param rule places a big layer over the mp axis; the
        step still runs and the param's state sharding honors the
        rule."""
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 16),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(16, 2))
        model = Model(net)
        model.prepare(
            paddle.optimizer.Adam(learning_rate=0.01,
                                  parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())

        def rule(name, param):
            if name == "0.weight":  # (4, 16): split the wide dim over mp
                return P(None, "mp")
            return None

        rs = np.random.RandomState(0)
        ds = TensorDataset([rs.randn(16, 4).astype("float32"),
                            rs.randint(0, 2, (16,)).astype("int64")])
        hist = model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                         verbose=0, mesh={"dp": 2, "mp": 4},
                         sharding_rule=rule)
        assert np.all(np.isfinite(hist["loss"]))
        eng = model._engine
        eng.begin(mesh={"dp": 2, "mp": 4}, sharding_rule=rule)
        assert eng._state_sharding["trainable"]["0.weight"].spec \
            == P(None, "mp")
        # Adam moments inherit the param's placement (same shape)
        for slot, sh in eng._state_sharding["opt"]["0.weight"].items():
            if sh.spec == P(None, "mp"):
                break
        else:
            pytest.fail("no opt slot inherited the mp sharding")
        eng.finish()

    def test_rule_vs_replicated_losses_match(self):
        def rule(name, param):
            return P(None, "mp") if name == "0.weight" else None

        def run(rule_):
            paddle.seed(0)
            net = paddle.nn.Sequential(paddle.nn.Linear(4, 16),
                                       paddle.nn.ReLU(),
                                       paddle.nn.Linear(16, 2))
            model = Model(net)
            model.prepare(
                paddle.optimizer.Adam(learning_rate=0.01,
                                      parameters=net.parameters()),
                paddle.nn.CrossEntropyLoss())
            rs = np.random.RandomState(0)
            ds = TensorDataset([rs.randn(16, 4).astype("float32"),
                                rs.randint(0, 2, (16,)).astype("int64")])
            return model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                             verbose=0, log_freq=1,
                             mesh={"dp": 2, "mp": 4}, sharding_rule=rule_)

        ha, hb = run(None), run(rule)
        np.testing.assert_allclose(ha["loss"], hb["loss"],
                                   rtol=2e-6, atol=1e-7)


# -- post-fit contracts ----------------------------------------------------
@needs8
class TestPostFitContracts:
    def test_layer_tree_is_single_device_after_sharded_fit(self):
        """write_back de-shards: the Layer tree never holds multi-device
        committed arrays, so evaluate/train_batch/save after a sharded
        fit stay mesh-free."""
        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
                  mesh={"dp": 8})
        for k, p in model.network.named_parameters():
            assert len(p._value.sharding.device_set) == 1, k
        res = model.evaluate(ds, batch_size=8, verbose=0)
        assert np.isfinite(res["loss"])
        rs = np.random.RandomState(1)
        model.train_batch(
            [paddle.to_tensor(rs.randn(8, 4).astype("float32"))],
            [paddle.to_tensor(rs.randint(0, 2, (8,)).astype("int64"))])

    def test_epoch_end_callback_sees_valid_weights(self):
        from paddle_tpu.hapi.callbacks import Callback

        seen = []

        class Peek(Callback):
            def on_epoch_end(self, epoch, logs=None):
                seen.append({k: np.asarray(p._value) for k, p in
                             self.model.network.named_parameters()})

        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0,
                  mesh={"dp": 8}, callbacks=[Peek()])
        assert len(seen) == 3
        assert any(not np.array_equal(seen[0][k], seen[2][k])
                   for k in seen[0])


# -- legacy DataParallel routing -------------------------------------------
@needs8
class TestDataParallelMeshRouting:
    def test_scale_loss_uses_ambient_mesh_dp_degree(self):
        import paddle_tpu.distributed.parallel as par

        dp = par.DataParallel(paddle.nn.Linear(2, 2))
        mesh = build_mesh({"dp": 4, "mp": 2})
        par._mesh_subsumed_warned = False
        try:
            with mesh_guard(mesh):
                with pytest.warns(DeprecationWarning,
                                  match="subsumes DataParallel"):
                    out = dp.scale_loss(paddle.to_tensor(8.0))
                # warn ONCE: the second call is silent
                with warnings.catch_warnings():
                    warnings.simplefilter("error")
                    out2 = dp.scale_loss(paddle.to_tensor(8.0))
            assert float(out.numpy()) == pytest.approx(2.0)   # / dp=4
            assert float(out2.numpy()) == pytest.approx(2.0)
        finally:
            par._mesh_subsumed_warned = False

    def test_scale_loss_without_mesh_uses_world_size(self):
        import paddle_tpu.distributed.parallel as par
        from paddle_tpu.distributed.mesh import set_mesh

        prev = get_mesh()
        try:
            set_mesh(None)  # pin: no global mesh from earlier tests
            dp = par.DataParallel(paddle.nn.Linear(2, 2))
            out = dp.scale_loss(paddle.to_tensor(8.0))  # world_size 1 → id
            assert float(out.numpy()) == pytest.approx(8.0)
        finally:
            set_mesh(prev)
