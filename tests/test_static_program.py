"""Static-graph program capture (fluid framework.py Program:4094 +
executor.py run:916): the classic program_guard -> data -> layers ->
minimize -> Executor.run workflow must train, on the tracing core."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


def test_multi_output_op_captures():
    """topk (a _multi_out op) must capture into the program as one
    shared op node whose outputs are index Variables — both outputs
    evaluate from a single op run and fetches agree with eager."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        vals, idx = paddle.topk(x, k=2, axis=-1)
        total = vals.sum()
    X = np.array([[0.0, 3.0, 1.0, 2.0]], np.float32)
    v, i, t = static.Executor().run(
        main, feed={"x": X}, fetch_list=[vals, idx, total])
    assert i.tolist() == [[1, 3]]
    np.testing.assert_allclose(v, [[3.0, 2.0]])
    np.testing.assert_allclose(float(t), 5.0)


class TestProgramCapture:
    def test_data_returns_symbolic_variable(self):
        x = static.data("x", [None, 4], "float32")
        assert x.name == "x"
        y = x * 2.0 + 1.0
        from paddle_tpu.static.program import Variable

        assert isinstance(y, Variable)

    def test_fetch_evaluation(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 3], "float32")
            y = (x * 2.0).sum()
        exe = static.Executor()
        out, = exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                       fetch_list=[y])
        assert float(out) == 12.0

    def test_layer_params_are_captured(self):
        paddle.seed(0)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            lin = paddle.nn.Linear(4, 2)
            out = lin(x)
        exe = static.Executor()
        r, = exe.run(main, feed={"x": np.ones((3, 4), np.float32)},
                     fetch_list=[out])
        expect = np.asarray(lin(paddle.to_tensor(
            np.ones((3, 4), np.float32))).numpy())
        np.testing.assert_allclose(r, expect, rtol=1e-5)

    def test_classic_fluid_training_loop(self):
        """The reference book pattern (tests/book/test_fit_a_line.py):
        program_guard + data + minimize + Executor.run loop converges."""
        paddle.seed(0)
        rs = np.random.RandomState(0)
        X = rs.randn(64, 4).astype(np.float32)
        W = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
        Y = X @ W

        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            y = static.data("y", [None, 1], "float32")
            lin = paddle.nn.Linear(4, 1)
            pred = lin(x)
            cost = ((pred - y) ** 2).mean()
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(cost)

        exe = static.Executor()
        exe.run(startup)
        losses = []
        for step in range(60):
            loss, = exe.run(main, feed={"x": X, "y": Y},
                            fetch_list=[cost])
            losses.append(float(loss))
        assert losses[-1] < 1e-3, losses[-5:]
        assert losses[-1] < losses[0] * 0.01
        # learned weights approach the generator
        w = np.asarray(lin.weight.numpy()).reshape(-1)
        np.testing.assert_allclose(w, W.reshape(-1), atol=0.05)

    def test_eval_clone_for_test(self):
        paddle.seed(0)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 2], "float32")
            lin = paddle.nn.Linear(2, 1)
            pred = lin(x)
            cost = (pred ** 2).mean()
            paddle.optimizer.SGD(learning_rate=0.1).minimize(cost)
        test_prog = main.clone(for_test=True)
        exe = static.Executor()
        # clone(for_test) must NOT train: params unchanged after run
        before = np.asarray(lin.weight.numpy()).copy()
        exe.run(test_prog, feed={"x": np.ones((2, 2), np.float32)},
                fetch_list=[cost])
        np.testing.assert_allclose(np.asarray(lin.weight.numpy()), before)
        # the train program DOES update
        exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                fetch_list=[cost])
        assert not np.allclose(np.asarray(lin.weight.numpy()), before)

    def test_missing_feed_is_loud(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 2], "float32")
            out = x.sum()
        with pytest.raises(ValueError, match="missing"):
            static.Executor().run(main, feed={}, fetch_list=[out])

    def test_shape_inference(self):
        x = static.data("x", [8, 4], "float32")
        lin = paddle.nn.Linear(4, 3)
        out = lin(x)
        assert out.shape == [8, 3]
