"""Round-5 surface tail (VERDICT r04 next-step #5): paddle.batch, the
reader decorator suite, DatasetFolder/ImageFolder, VOC2012, Conll05st,
compat, sysconfig, utils.download, incubate."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_batch_reader():
    def reader():
        yield from range(10)

    assert list(paddle.batch(reader, 3)()) == [
        [0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    assert list(paddle.batch(reader, 3, drop_last=True)()) == [
        [0, 1, 2], [3, 4, 5], [6, 7, 8]]
    with pytest.raises(ValueError):
        paddle.batch(reader, 0)


def test_reader_decorators():
    rd = paddle.reader

    def r5():
        yield from range(5)

    assert list(rd.cache(r5)()) == [0, 1, 2, 3, 4]
    assert list(rd.map_readers(lambda a, b: a + b, r5, r5)()) == [
        0, 2, 4, 6, 8]
    assert list(rd.chain(r5, r5)()) == list(range(5)) * 2
    assert list(rd.firstn(r5, 3)()) == [0, 1, 2]
    assert list(rd.buffered(r5, 2)()) == [0, 1, 2, 3, 4]
    # compose: tuple-flattening zip; misaligned lengths raise
    got = list(rd.compose(r5, rd.map_readers(lambda x: (x, x), r5))())
    assert got[2] == (2, 2, 2)
    def r3():
        yield from range(3)
    with pytest.raises(rd.ComposeNotAligned):
        list(rd.compose(r5, r3)())
    assert len(list(rd.compose(r5, r3, check_alignment=False)())) == 3
    # shuffle: same multiset, reproducible under paddle.seed
    paddle.seed(123)
    a = list(rd.shuffle(r5, 5)())
    paddle.seed(123)
    b = list(rd.shuffle(r5, 5)())
    assert sorted(a) == [0, 1, 2, 3, 4] and a == b
    # xmap: unordered covers all, ordered preserves order
    out = list(rd.xmap_readers(lambda x: x * 10, r5, 2, 4)())
    assert sorted(out) == [0, 10, 20, 30, 40]
    out = list(rd.xmap_readers(lambda x: x * 10, r5, 3, 4, order=True)())
    assert out == [0, 10, 20, 30, 40]


def test_dataset_folder(tmp_path):
    from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for j in range(2):
            np.save(d / f"{j}.npy",
                    np.full((4, 4, 3), j, dtype=np.float32))
    ds = DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    assert len(ds) == 4 and ds.targets.count(1) == 2
    sample, target = ds[3]
    assert sample.shape == (4, 4, 3) and target == 1
    # transform applies
    ds2 = DatasetFolder(str(tmp_path), transform=lambda x: x + 1)
    assert float(ds2[0][0][0, 0, 0]) == 1.0
    flat = ImageFolder(str(tmp_path))
    assert len(flat) == 4 and isinstance(flat[0], list)
    with pytest.raises(RuntimeError):
        DatasetFolder(str(tmp_path), extensions=(".jpg",))


def test_dataset_folder_pil(tmp_path):
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    from paddle_tpu.vision.datasets import DatasetFolder
    d = tmp_path / "a"
    d.mkdir()
    Image.fromarray(np.zeros((5, 6, 3), np.uint8)).save(d / "x.png")
    ds = DatasetFolder(str(tmp_path))
    img, target = ds[0]
    assert np.asarray(img).shape == (5, 6, 3) and target == 0


def test_voc2012_synthetic():
    from paddle_tpu.vision.datasets import VOC2012
    ds = VOC2012(mode="train")
    assert ds.synthetic and len(ds) == 64
    img, lab = ds[0]
    assert img.shape == (64, 64, 3) and lab.shape == (64, 64)
    assert lab.max() <= 20
    with pytest.raises(AssertionError):
        VOC2012(mode="bogus")


def test_conll05():
    from paddle_tpu.text import Conll05st
    ds = Conll05st()
    assert ds.synthetic and len(ds) == 80
    sample = ds[0]
    assert len(sample) == 9
    n = len(sample[0])
    assert all(len(col) == n for col in sample)
    word_d, verb_d, label_d = ds.get_dict()
    assert "B-V" in label_d
    # mark flags the <=5-token predicate window
    assert 1 <= sample[7].sum() <= 5
    # the ctx_0 column is the predicate itself, broadcast
    vi = list(ds.labels[0]).index("B-V")
    assert sample[3][0] == word_d[ds.sentences[0][vi]]


def test_compat():
    c = paddle.compat
    assert c.to_text(b"ab") == "ab"
    assert c.to_bytes("ab") == b"ab"
    assert c.to_text({b"k"}) == {"k"}
    lst = [b"x", [b"y"]]
    c.to_text(lst, inplace=True)
    assert lst == ["x", ["y"]]
    assert c.round(2.5) == 3.0 and c.round(-2.5) == -3.0
    assert c.round(2.345, 2) == 2.35
    assert c.floor_division(7, 2) == 3
    assert c.get_exception_message(ValueError("boom")) == "boom"


def test_sysconfig():
    inc = paddle.sysconfig.get_include()
    assert os.path.basename(inc) == "csrc"
    assert isinstance(paddle.sysconfig.get_lib(), str)


def test_download_cache(tmp_path):
    from paddle_tpu.utils.download import get_path_from_url
    # pre-seeded cache file is returned without any network touch
    f = tmp_path / "weights.bin"
    f.write_bytes(b"abc")
    got = get_path_from_url("http://example.invalid/weights.bin",
                            str(tmp_path))
    assert got == str(f)
    with pytest.raises(RuntimeError, match="local cache"):
        get_path_from_url("http://example.invalid/missing.bin",
                          str(tmp_path))


def test_incubate():
    assert paddle.incubate.optimizer.LookAhead is not None
    assert paddle.incubate.optimizer.ModelAverage is not None
    assert paddle.incubate.reader is paddle.reader


def test_fleet_optimizer_facade():
    import paddle_tpu.distributed.fleet as fleet
    opt = paddle.optimizer.SGD(learning_rate=0.5)
    fopt = fleet.distributed_optimizer(opt)
    assert fleet.fleet.get_lr() == 0.5
    fleet.fleet.set_lr(0.25)
    assert fopt.get_lr() == 0.25
