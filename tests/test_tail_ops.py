"""Round-4 op-registry tail — OpTest-style numpy-reference coverage for
the ops COVERAGE.md flipped to implemented (reference kernels:
sequence_ops/*.cc, metrics/*.cc, detection/*.cc, and assorted singles —
see each op's docstring for its file:line citation)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.text import sequence as sq
from paddle_tpu.vision import ops as V


def T(x):
    return paddle.to_tensor(np.asarray(x))


class TestSequenceOps:
    def test_pool_types(self):
        x = np.array([[1.0, 2.0, 3.0, 9.0], [4.0, 9.0, 9.0, 9.0]],
                     np.float32)
        ln = np.array([3, 1])
        assert np.allclose(
            np.asarray(sq.sequence_pool(T(x), T(ln), "SUM").numpy()),
            [6.0, 4.0])
        assert np.allclose(
            np.asarray(sq.sequence_pool(T(x), T(ln), "AVERAGE").numpy()),
            [2.0, 4.0])
        assert np.allclose(
            np.asarray(sq.sequence_pool(T(x), T(ln), "MAX").numpy()),
            [3.0, 4.0])
        assert np.allclose(
            np.asarray(sq.sequence_pool(T(x), T(ln), "LAST").numpy()),
            [3.0, 4.0])
        assert np.allclose(
            np.asarray(sq.sequence_pool(T(x), T(ln), "SQRT").numpy()),
            [6.0 / np.sqrt(3), 4.0])

    def test_softmax_masks_padding(self):
        x = np.zeros((1, 4), np.float32)
        out = np.asarray(sq.sequence_softmax(T(x), T(np.array([2]))).numpy())
        assert np.allclose(out, [[0.5, 0.5, 0, 0]])

    def test_reverse_valid_prefix_only(self):
        x = np.array([[1, 2, 3, 9]], np.float32)
        out = np.asarray(
            sq.sequence_reverse(T(x), T(np.array([3]))).numpy())
        assert np.allclose(out, [[3, 2, 1, 9]])

    def test_conv_matches_numpy_window(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 5, 2).astype(np.float32)
        w = rs.randn(3 * 2, 4).astype(np.float32)
        ln = np.array([4])
        out = np.asarray(sq.sequence_conv(
            T(x), T(ln), T(w), context_length=3).numpy())
        # numpy reference: window [-1,0,1], zero outside [0, len)
        exp = np.zeros((1, 5, 4), np.float32)
        for t in range(4):
            ctx = []
            for o in (-1, 0, 1):
                s = t + o
                ctx.append(x[0, s] if 0 <= s < 4 else np.zeros(2))
            exp[0, t] = np.concatenate(ctx) @ w
        np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)

    def test_pad_unpad_roundtrip(self):
        flat = np.arange(10, dtype=np.float32).reshape(5, 2)
        ln = np.array([3, 2])
        padded, lens = sq.sequence_pad(T(flat), T(ln))
        assert np.asarray(padded.numpy()).shape == (2, 3, 2)
        back = sq.sequence_unpad(padded, lens)
        np.testing.assert_allclose(np.asarray(back.numpy()), flat)

    def test_expand_and_expand_as(self):
        x = np.array([[1.0], [2.0], [3.0]], np.float32)
        out = np.asarray(sq.sequence_expand_as(
            T(x), T(np.array([2, 0, 1]))).numpy())
        np.testing.assert_allclose(out, [[1], [1], [3]])
        out2 = np.asarray(sq.sequence_expand(
            T(x), T(np.array([2, 1])), T(np.array([2, 3]))).numpy())
        # first block (rows 0-1) twice, second block (row 2) three times
        np.testing.assert_allclose(
            out2.ravel(), [1, 2, 1, 2, 3, 3, 3])

    def test_enumerate_windows(self):
        ids = np.array([[1, 2, 3, 0]])
        out = np.asarray(sq.sequence_enumerate(
            T(ids), T(np.array([3])), win_size=2, pad_value=9).numpy())
        np.testing.assert_allclose(
            out[0], [[1, 2], [2, 3], [3, 9], [9, 9]])

    def test_slice_and_scatter(self):
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        out, lens = sq.sequence_slice(
            T(x), T(np.array([4, 4])), T(np.array([1, 0])),
            T(np.array([2, 3])))
        o = np.asarray(out.numpy())
        np.testing.assert_allclose(o[0, :2], [1, 2])
        np.testing.assert_allclose(o[1, :3], [4, 5, 6])
        base = np.zeros((1, 5), np.float32)
        upd = np.array([[1.0, 2.0, 9.0]], np.float32)
        idx = np.array([[0, 3, 4]])
        res = np.asarray(sq.sequence_scatter(
            T(base), T(idx), T(upd), T(np.array([2]))).numpy())
        np.testing.assert_allclose(res, [[1, 0, 0, 2, 0]])

    def test_concat_packs_left(self):
        a = np.array([[1, 2, 0]], np.float32)
        b = np.array([[3, 4, 0]], np.float32)
        out, lens = sq.sequence_concat(
            [T(a), T(b)], [T(np.array([2])), T(np.array([1]))])
        o = np.asarray(out.numpy())
        np.testing.assert_allclose(o[0, :3], [1, 2, 3])
        assert int(np.asarray(lens.numpy())[0]) == 3

    def test_reshape(self):
        flat = np.arange(12, dtype=np.float32).reshape(6, 2)
        out, lens = sq.sequence_reshape(T(flat), T(np.array([4, 2])), 4)
        assert np.asarray(out.numpy()).shape == (3, 4)
        np.testing.assert_allclose(np.asarray(lens.numpy()), [2, 1])


class TestFunctionalTail:
    def test_hinge_log_rank_bpr(self):
        x = np.array([0.5, -0.5], np.float32)
        y = np.array([1.0, 0.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(F.hinge_loss(T(x), T(y)).numpy()), [0.5, 0.5])
        p = np.array([0.9, 0.1], np.float32)
        exp = -(y * np.log(p + 1e-4) + (1 - y) * np.log(1 - p + 1e-4))
        np.testing.assert_allclose(
            np.asarray(F.log_loss(T(p), T(y)).numpy()), exp, rtol=1e-5)
        l, r = np.array([2.0]), np.array([1.0])
        exp_r = np.log1p(np.exp(1.0)) - 1.0
        np.testing.assert_allclose(
            np.asarray(F.rank_loss(T(np.array([1.0])), T(l), T(r)).numpy()),
            [exp_r], rtol=1e-5)
        logits = np.array([[2.0, 1.0, 0.0]], np.float32)
        lab = np.array([0])
        got = float(np.asarray(F.bpr_loss(T(logits), T(lab)).numpy()))
        exp_b = -np.mean([np.log(1 / (1 + np.exp(-(2 - 1)))),
                          np.log(1 / (1 + np.exp(-(2 - 0))))])
        assert abs(got - exp_b) < 1e-5

    def test_bilinear(self):
        rs = np.random.RandomState(0)
        a = rs.randn(2, 3).astype(np.float32)
        b = rs.randn(2, 4).astype(np.float32)
        w = rs.randn(5, 3, 4).astype(np.float32)
        out = np.asarray(F.bilinear(T(a), T(b), T(w)).numpy())
        exp = np.einsum("bm,omn,bn->bo", a, w, b)
        np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)

    def test_conv_shift(self):
        x = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
        y = np.array([[1.0, 0.0, 0.0]], np.float32)  # pick left neighbor
        out = np.asarray(F.conv_shift(T(x), T(y)).numpy())
        np.testing.assert_allclose(out, [[4, 1, 2, 3]])

    def test_ctc_align(self):
        ids = np.array([[1, 1, 0, 2, 2, 3]])
        out, lens = F.ctc_align(T(ids), T(np.array([6])), blank=0)
        np.testing.assert_allclose(np.asarray(out.numpy())[0, :3],
                                   [1, 2, 3])
        assert int(np.asarray(lens.numpy())[0]) == 3
        # the paddle-standard [B,1] length layout must work too
        out2, lens2 = F.ctc_align(T(ids), T(np.array([[6]])), blank=0)
        np.testing.assert_allclose(np.asarray(out2.numpy()),
                                   np.asarray(out.numpy()))

    def test_center_loss_updates_centers(self):
        x = np.array([[1.0, 1.0]], np.float32)
        c = np.zeros((2, 2), np.float32)
        loss, newc = F.center_loss(T(x), T(np.array([1])), T(c), alpha=0.5)
        assert abs(float(np.asarray(loss.numpy())[0, 0]) - 1.0) < 1e-6
        nc = np.asarray(newc.numpy())
        np.testing.assert_allclose(nc[1], [0.25, 0.25])  # alpha*d/(1+1)

    def test_row_conv(self):
        x = np.arange(6, dtype=np.float32).reshape(1, 3, 2)
        w = np.array([[1.0, 1.0], [1.0, 1.0]], np.float32)  # t and t+1
        out = np.asarray(F.row_conv(T(x), T(w)).numpy())
        np.testing.assert_allclose(out[0, 0], x[0, 0] + x[0, 1])
        np.testing.assert_allclose(out[0, 2], x[0, 2])  # no lookahead left

    def test_spp_output_dim(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        out = np.asarray(F.spp(T(x), pyramid_height=2).numpy())
        assert out.shape == (2, 3 * (1 + 4))

    def test_spp_non_divisible_matches_ceil_kernel(self):
        # reference spp_op.h: kernel=ceil(H/bins) -> bin (0,0) of a 5x5
        # covers rows/cols [0:3] (floor-start/ceil-end convention)
        x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
        out = np.asarray(F.spp(T(x), pyramid_height=2).numpy())[0]
        # level 0: global max 24; level 1 bins: [0:3,0:3]->12,
        # [0:3,2:5]->14, [2:5,0:3]->22, [2:5,2:5]->24
        np.testing.assert_allclose(out, [24, 12, 14, 22, 24])

    def test_max_unpool2d_roundtrip(self):
        x = np.array([[[[5.0, 6.0], [7.0, 8.0]]]], np.float32)
        idx = np.array([[[[0, 3], [8, 11]]]])  # flat positions in 3x4
        out = np.asarray(F.max_unpool2d(
            T(x), T(idx), kernel_size=2, stride=2,
            output_size=(3, 4)).numpy())
        assert out.shape == (1, 1, 3, 4)
        assert out[0, 0, 0, 0] == 5.0 and out[0, 0, 0, 3] == 6.0
        assert out[0, 0, 2, 0] == 7.0 and out[0, 0, 2, 3] == 8.0

    def test_add_position_encoding_alpha_beta(self):
        x = np.zeros((1, 3, 4), np.float32)
        out = np.asarray(F.add_position_encoding(T(x), 1.0, 1.0).numpy())
        # pos 0: sin(0)=0, cos(0)=1 -> first half 0, second half 1
        np.testing.assert_allclose(out[0, 0], [0, 0, 1, 1], atol=1e-6)


class TestTensorOpsTail:
    def test_slice_and_strided(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = np.asarray(paddle.slice(T(x), [0, 1], [1, 1], [3, 3]).numpy())
        np.testing.assert_allclose(out, x[1:3, 1:3])
        out = np.asarray(paddle.strided_slice(
            T(x), [1], [0], [4], [2]).numpy())
        np.testing.assert_allclose(out, x[:, ::2])

    def test_add_n_addmm_segment(self):
        x = np.ones((2, 2), np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.add_n([T(x), T(2 * x)]).numpy()), 3 * x)
        a = np.arange(4, dtype=np.float32).reshape(2, 2)
        out = np.asarray(paddle.addmm(
            T(np.ones((2, 2), np.float32)), T(a), T(a),
            beta=2.0, alpha=1.0).numpy())
        np.testing.assert_allclose(out, 2.0 + a @ a)
        seg = np.asarray(paddle.segment_sum(
            T(np.arange(6, dtype=np.float32).reshape(3, 2)),
            T(np.array([0, 0, 1]))).numpy())
        np.testing.assert_allclose(seg, [[2, 4], [4, 5]])

    def test_inverse_cholesky_stanh(self):
        m = np.array([[2.0, 0.0], [0.0, 4.0]], np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.inverse(T(m)).numpy()),
            np.linalg.inv(m), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.cholesky(T(m)).numpy()),
            np.linalg.cholesky(m), rtol=1e-5)
        v = float(np.asarray(paddle.stanh(
            T(np.float32(1.0)), 0.5, 2.0).numpy()))
        assert abs(v - 2.0 * np.tanh(0.5)) < 1e-6


class TestMetricTail:
    def test_mean_iou_against_confusion(self):
        pred = np.array([0, 0, 1, 1])
        lab = np.array([0, 1, 1, 1])
        m, iou, _ = paddle.metric.mean_iou(T(pred), T(lab), 2)
        # class0: inter 1, union 2 -> .5 ; class1: inter 2, union 3
        np.testing.assert_allclose(
            np.asarray(iou.numpy()), [0.5, 2 / 3], rtol=1e-5)

    def test_edit_distance_known_pairs(self):
        d, n = paddle.metric.edit_distance(
            T(np.array([[1, 2, 3]])), T(np.array([3])),
            T(np.array([[1, 3, 0]])), T(np.array([2])), normalized=False)
        assert float(np.asarray(d.numpy())[0, 0]) == 1.0
        assert n == 1

    def test_chunk_evaluator_outside_tag(self):
        # num_chunk_types=1: tags 0=B, 1=I, 2=O; O runs are not chunks
        ce = paddle.metric.ChunkEvaluator(num_chunk_types=1)
        inf = np.array([[0, 1, 2, 2, 0]])
        lab = np.array([[0, 1, 2, 2, 0]])
        ce.update(inf, lab, np.array([5]))
        p, r, f1 = ce.accumulate()
        assert (p, r, f1) == (1.0, 1.0, 1.0)
        assert ce._label == 2  # two chunks, not an O-phantom third
        with pytest.raises(ValueError):
            paddle.metric.ChunkEvaluator(scheme="BILOU")

    def test_chunk_evaluator_io_runs(self):
        # IO: maximal same-type runs are ONE chunk (not per-token)
        ce = paddle.metric.ChunkEvaluator(scheme="IO", num_chunk_types=2)
        lab = np.array([[0, 0, 2, 1, 1]])   # run of type0, O, run of type1
        pred = np.array([[0, 2, 2, 1, 1]])  # boundary error on the first
        ce.update(pred, lab, np.array([5]))
        p, r, f1 = ce.accumulate()
        assert ce._label == 2 and ce._infer == 2
        assert ce._correct == 1  # only the type-1 run matches exactly
        assert (p, r) == (0.5, 0.5)

    def test_chunk_evaluator_ioe_and_iobes(self):
        # IOE (roles I,E): chunk [I I E] of type 0 = tags [0, 0, 1]
        ce = paddle.metric.ChunkEvaluator(scheme="IOE", num_chunk_types=1)
        seq = np.array([[0, 0, 1, 2]])     # I I E O -> one chunk [0,3)
        ce.update(seq, seq, np.array([4]))
        assert ce._label == 1 and ce._correct == 1
        # IOBES (roles B,I,E,S): B I E then S then O
        ce2 = paddle.metric.ChunkEvaluator(scheme="IOBES",
                                           num_chunk_types=2)
        # type0: B=0 I=1 E=2 S=3; type1: B=4 I=5 E=6 S=7; O=8
        seq2 = np.array([[0, 1, 2, 3, 8, 4, 6]])
        ce2.update(seq2, seq2, np.array([7]))
        # chunks: [0,3) type0; [3,4) S type0; [5,6) B-type1 cut by E;
        # conlleval: B then E of same type = one chunk [5,7)
        p, r, f1 = ce2.accumulate()
        assert (p, r, f1) == (1.0, 1.0, 1.0)
        assert ce2._label == 3

    def test_bpr_loss_column_label(self):
        logits = np.array([[2.0, 1.0, 0.0]], np.float32)
        a = float(np.asarray(paddle.nn.functional.bpr_loss(
            T(logits), T(np.array([0]))).numpy()))
        b = float(np.asarray(paddle.nn.functional.bpr_loss(
            T(logits), T(np.array([[0]]))).numpy()))
        assert abs(a - b) < 1e-7

    def test_segment_sum_jit_requires_num_segments(self):
        import jax

        data = np.arange(6, dtype=np.float32).reshape(3, 2)
        ids = np.array([0, 0, 1])
        out = jax.jit(lambda d, i: paddle.segment_sum(
            d, i, num_segments=2).value)(data, ids)
        np.testing.assert_allclose(np.asarray(out), [[2, 4], [4, 5]])
        with pytest.raises(ValueError, match="num_segments"):
            jax.jit(lambda d, i: paddle.segment_sum(d, i).value)(
                data, ids)

    def test_precision_recall_micro(self):
        pr = paddle.metric.PrecisionRecall(2)
        pr.update(np.array([1, 1, 0, 0]), np.array([1, 0, 0, 0]))
        mp, mr, mf, up, ur, uf = pr.accumulate()
        assert abs(up - 0.75) < 1e-9 and abs(ur - 0.75) < 1e-9

    def test_precision_recall_float_and_out_of_range(self):
        pr = paddle.metric.PrecisionRecall(3)
        # float labels must not crash; out-of-range prediction counts as
        # FN for its label class, not as an aliased confusion cell
        pr.update(np.array([0, 5, 1]), np.array([0.0, 1.0, 1.0]))
        assert pr._tp.tolist() == [1, 1, 0]
        assert pr._fn.tolist() == [0, 1, 0]
        assert pr._fp.tolist() == [0, 0, 0]

    def test_detection_map_half(self):
        dm = paddle.metric.DetectionMAP()
        dm.update(np.array([[0, 0, 10, 10], [50, 50, 60, 60]]),
                  np.array([0.9, 0.8]), np.array([1, 1]),
                  np.array([[0, 0, 10, 10], [100, 100, 110, 110]]),
                  np.array([1, 1]))
        # 1 TP of 2 gts, 1 FP -> AP = 0.5
        assert abs(dm.accumulate() - 0.5) < 1e-6


class TestTextDecode:
    def test_gather_tree_reference_example(self):
        ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                        [[0, 1], [9, 0]]], np.int64)
        par = np.array([[[0, 0], [1, 1]], [[1, 0], [0, 0]],
                        [[0, 0], [0, 1]]], np.int64)
        from paddle_tpu.text import gather_tree
        out = np.asarray(gather_tree(T(ids), T(par)).numpy())
        exp = [[[2, 2], [6, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]]
        np.testing.assert_allclose(out, exp)

    def test_beam_search_step_topk(self):
        from paddle_tpu.text import beam_search_step
        lp = np.log(np.array([[[0.1, 0.6, 0.3],
                               [0.5, 0.4, 0.1]]], np.float32))
        ids, par, sc = beam_search_step(
            T(lp), T(np.zeros((1, 2), np.float32)), 2)
        assert np.asarray(ids.numpy()).tolist() == [[1, 0]]
        assert np.asarray(par.numpy()).tolist() == [[0, 1]]

    def test_linear_chain_crf_trains(self):
        from paddle_tpu.text import linear_chain_crf
        rs = np.random.RandomState(0)
        em = T(rs.randn(2, 4, 3).astype(np.float32))
        tr = T(rs.randn(5, 3).astype(np.float32))
        lab = T(np.array([[0, 1, 2, 1], [2, 0, 0, 0]]))
        ln = T(np.array([4, 2]))
        ll = np.asarray(linear_chain_crf(em, tr, lab, ln).numpy())
        assert (ll < 0).all()  # log-likelihood of a gold path
        # exact check on a tiny case: T=1 reduces to softmax over start+em
        em1 = np.array([[[1.0, 2.0, 3.0]]], np.float32)
        tr1 = np.zeros((5, 3), np.float32)
        ll1 = float(np.asarray(linear_chain_crf(
            T(em1), T(tr1), T(np.array([[2]])), T(np.array([1]))).numpy()))
        exp = 3.0 - np.log(np.exp([1, 2, 3]).sum())
        assert abs(ll1 - exp) < 1e-5


class TestVisionTail:
    def test_deform_conv_zero_offset_equals_conv(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 4, 6, 6).astype(np.float32)
        w = rs.randn(3, 4, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 6, 6), np.float32)
        got = np.asarray(V.deform_conv2d(
            T(x), T(off), T(w), stride=1, padding=1).numpy())
        exp = np.asarray(F.conv2d(T(x), T(w), stride=1, padding=1).numpy())
        np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)

    def test_deform_conv_half_mask_halves_output(self):
        rs = np.random.RandomState(1)
        x = rs.randn(1, 2, 4, 4).astype(np.float32)
        w = rs.randn(2, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 4, 4), np.float32)
        m1 = np.ones((1, 9, 4, 4), np.float32)
        a = np.asarray(V.deform_conv2d(
            T(x), T(off), T(w), mask=T(m1), padding=1).numpy())
        b = np.asarray(V.deform_conv2d(
            T(x), T(off), T(w), mask=T(0.5 * m1), padding=1).numpy())
        np.testing.assert_allclose(b, 0.5 * a, rtol=1e-4, atol=1e-6)

    def test_space_to_depth_numpy_ref(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = np.asarray(V.space_to_depth(T(x), 2).numpy())
        assert out.shape == (1, 4, 2, 2)
        np.testing.assert_allclose(out[0, 0], [[0, 2], [8, 10]])

    def test_channel_shuffle_ref(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 4, 1, 2)
        out = np.asarray(V.channel_shuffle(T(x), 2).numpy())
        # groups=2: [0,1,2,3] -> [0,2,1,3]
        np.testing.assert_allclose(out[0, :, 0, 0], [0, 4, 2, 6])

    def test_psroi_pool_channel_major_layout(self):
        # reference layout (psroi_pool_op.h:125): output channel c at bin
        # (ph,pw) reads input channel (c*ph_total+ph)*pw_total+pw
        x = np.zeros((1, 8, 4, 4), np.float32)
        for ch in range(8):
            x[0, ch] = ch
        out = np.asarray(V.psroi_pool(
            T(x), T(np.array([[0, 0, 3.9, 3.9]], np.float32)),
            output_size=2, output_channels=2).numpy())
        for c in range(2):
            for ph in range(2):
                for pw in range(2):
                    assert out[0, c, ph, pw] == (c * 2 + ph) * 2 + pw

    def test_psroi_prroi_batch_roi_assignment(self):
        # rois must pool from THEIR image (boxes_num), not image 0
        x = np.zeros((2, 4, 4, 4), np.float32)
        x[1] = 7.0
        rois = np.array([[0, 0, 3.9, 3.9], [0, 0, 3.9, 3.9]], np.float32)
        bn = np.array([1, 1])
        ps = np.asarray(V.psroi_pool(T(x), T(rois), boxes_num=T(bn),
                                     output_size=2,
                                     output_channels=1).numpy())
        assert ps[0].max() == 0.0 and ps[1].min() == 7.0
        rois_in = np.array([[0, 0, 3, 3], [0, 0, 3, 3]], np.float32)
        pr = np.asarray(V.prroi_pool(T(x), T(rois_in), boxes_num=T(bn),
                                     output_size=2).numpy())
        assert pr[0].max() == 0.0 and abs(pr[1].mean() - 7.0) < 1e-5

    def test_channel_shuffle_nhwc(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 1, 2, 4)  # NHWC C=4
        out = np.asarray(V.channel_shuffle(T(x), 2,
                                           data_format="NHWC").numpy())
        np.testing.assert_allclose(out[0, 0, 0], [0, 2, 1, 3])
        with pytest.raises(ValueError):
            V.channel_shuffle(T(x), 2, data_format="NCW")

    def test_prroi_pool_constant_field(self):
        x = np.full((1, 3, 6, 6), 2.5, np.float32)
        out = np.asarray(V.prroi_pool(
            T(x), T(np.array([[1, 1, 5, 5]], np.float32)),
            output_size=2).numpy())
        np.testing.assert_allclose(out, np.full((1, 3, 2, 2), 2.5),
                                   rtol=1e-5)

    def test_rpn_target_assign_thresholds(self):
        anchors = np.array([[0, 0, 10, 10], [0, 0, 9, 11],
                            [100, 100, 110, 110]], np.float32)
        gt = np.array([[0, 0, 10, 10]], np.float32)
        fg, si, lab, tgt = V.rpn_target_assign(
            anchors, gt, rpn_positive_overlap=0.7,
            rpn_negative_overlap=0.3)
        fg = np.asarray(fg.numpy())
        assert 0 in fg  # exact-match anchor is foreground
        assert np.asarray(tgt.numpy()).shape[1] == 4

    def test_generate_proposal_labels_samples(self):
        rois = np.array([[0, 0, 10, 10], [100, 100, 120, 120]], np.float32)
        rlab, lab, tgt = V.generate_proposal_labels(
            rois, np.array([3]), np.array([[0, 0, 10, 10]], np.float32),
            batch_size_per_im=4)
        lab = np.asarray(lab.numpy())
        assert (lab == 3).sum() >= 1  # the matching roi keeps its class
        assert (lab == 0).sum() >= 1  # background sampled

    def test_yolo_loss_finite_and_differentiable(self):
        rs = np.random.RandomState(0)
        x = T(rs.randn(1, 3 * 9, 4, 4).astype(np.float32))
        x.stop_gradient = False
        gb = T(np.array([[[0.5, 0.5, 0.3, 0.4]]], np.float32))
        gl = T(np.array([[1]]))
        loss = V.yolo_loss(x, gb, gl, anchors=[10, 13, 16, 30, 33, 23],
                           anchor_mask=[0, 1, 2], class_num=4)
        val = float(np.asarray(loss.numpy()))
        assert np.isfinite(val) and val > 0
        loss.backward()
        g = np.asarray(x.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_yolo_loss_numeric_parity(self):
        """Hand-computed reference value (yolov3_loss_op.h semantics:
        sigmoid cross-entropy on raw x/y logits, L1 on w/h, every per-gt
        term scaled by gt_score, objectness target = score)."""
        # 1 anchor (16x16 px), 2x2 grid, stride 32, one gt at cell (1,1)
        H = W = 2
        xv = np.full((1, 1 * 7, H, W), 0.1, np.float32)  # 5+C, C=2
        gb = np.array([[[0.75, 0.75, 0.25, 0.25]]], np.float32)
        gl = np.array([[1]])
        gs = np.array([[0.5]], np.float32)
        loss = V.yolo_loss(T(xv), T(gb), T(gl), anchors=[16, 16],
                           anchor_mask=[0], class_num=2,
                           ignore_thresh=2.0,  # no cell is ignored
                           downsample_ratio=32, gt_score=T(gs))

        def bce(z, t):
            return max(z, 0.0) - z * t + np.log1p(np.exp(-abs(z)))

        tx = ty = 0.5           # gx = gy = 1.5
        tw = th = 0.0           # gt wh == anchor wh (16 px)
        scale = 2.0 - 0.25 * 0.25
        m = scale * 0.5         # resp * scale * gt_score
        exp_xy = m * (bce(0.1, tx) + bce(0.1, ty))
        exp_wh = m * (abs(0.1 - tw) + abs(0.1 - th))
        exp_cls = 0.5 * (bce(0.1, 0.0) + bce(0.1, 1.0))
        # positive cell: SCE vs score; 3 negatives: SCE vs 0
        exp_obj = bce(0.1, 0.5) + 3 * bce(0.1, 0.0)
        expected = exp_xy + exp_wh + exp_cls + exp_obj
        np.testing.assert_allclose(float(np.asarray(loss.numpy())),
                                   expected, rtol=1e-5)

    def test_correlation_numpy_reference(self):
        rs = np.random.RandomState(0)
        a = rs.randn(1, 4, 6, 6).astype(np.float32)
        b = rs.randn(1, 4, 6, 6).astype(np.float32)
        out = np.asarray(V.correlation(
            T(a), T(b), pad_size=2, kernel_size=1,
            max_displacement=2).numpy())
        bp = np.pad(b, ((0, 0), (0, 0), (2, 2), (2, 2)))
        k = 0
        for dy in range(-2, 3):
            for dx in range(-2, 3):
                exp = (a * bp[:, :, 2 + dy:8 + dy,
                              2 + dx:8 + dx]).mean(1)
                np.testing.assert_allclose(out[:, k], exp,
                                           rtol=1e-4, atol=1e-5)
                k += 1


class TestStaticPrint:
    def test_print_passthrough(self, capsys):
        x = T(np.array([1.0, 2.0]))
        out = paddle.static.Print(x, message="dbg")
        np.testing.assert_allclose(np.asarray(out.numpy()), [1, 2])
        assert "dbg" in capsys.readouterr().out
