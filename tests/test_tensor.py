"""Tensor surface tests (OpTest-style numpy-reference checks, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = paddle.to_tensor(a)
    assert t.shape == [3, 4]
    assert str(t.dtype) == "float32"
    np.testing.assert_allclose(t.numpy(), a)


def test_default_float64_downcast():
    t = paddle.to_tensor(np.zeros(3))  # float64 numpy -> default dtype
    assert str(t.dtype) == "float32"


def test_arithmetic_matches_numpy():
    a = np.random.rand(4, 5).astype(np.float32)
    b = np.random.rand(4, 5).astype(np.float32) + 0.5
    ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
    np.testing.assert_allclose((ta + tb).numpy(), a + b, rtol=1e-6)
    np.testing.assert_allclose((ta - tb).numpy(), a - b, rtol=1e-6)
    np.testing.assert_allclose((ta * tb).numpy(), a * b, rtol=1e-6)
    np.testing.assert_allclose((ta / tb).numpy(), a / b, rtol=1e-5)
    np.testing.assert_allclose((ta ** 2).numpy(), a ** 2, rtol=1e-6)
    np.testing.assert_allclose((-ta).numpy(), -a)
    np.testing.assert_allclose((ta @ tb.T).numpy(), a @ b.T, rtol=1e-5)


def test_scalar_mixing():
    t = paddle.to_tensor([1.0, 2.0])
    np.testing.assert_allclose((2 * t + 1).numpy(), [3.0, 5.0])
    np.testing.assert_allclose((1 - t).numpy(), [0.0, -1.0])


def test_reductions():
    a = np.random.rand(3, 4, 5).astype(np.float32)
    t = paddle.to_tensor(a)
    np.testing.assert_allclose(paddle.sum(t).numpy(), a.sum(), rtol=1e-5)
    np.testing.assert_allclose(paddle.mean(t, axis=1).numpy(), a.mean(1),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.max(t, axis=-1, keepdim=True).numpy(),
                               a.max(-1, keepdims=True))
    np.testing.assert_allclose(paddle.prod(t, axis=0).numpy(), a.prod(0),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.std(t).numpy(), a.std(ddof=1), rtol=1e-4)


def test_manipulation():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    t = paddle.to_tensor(a)
    assert paddle.reshape(t, [0, -1]).shape == [2, 12]  # 0 = copy dim
    assert paddle.transpose(t, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.squeeze(paddle.unsqueeze(t, 0), 0).shape == [2, 3, 4]
    assert paddle.flatten(t, 1).shape == [2, 12]
    c = paddle.concat([t, t], axis=1)
    assert c.shape == [2, 6, 4]
    s = paddle.split(t, 3, axis=1)
    assert len(s) == 3 and s[0].shape == [2, 1, 4]
    s2 = paddle.split(t, [1, -1], axis=2)
    assert s2[1].shape == [2, 3, 3]
    st = paddle.stack([t, t], axis=0)
    assert st.shape == [2, 2, 3, 4]
    assert paddle.tile(t, [2, 1, 1]).shape == [4, 3, 4]


def test_indexing_and_gather():
    a = np.arange(20, dtype=np.float32).reshape(4, 5)
    t = paddle.to_tensor(a)
    np.testing.assert_allclose(t[1].numpy(), a[1])
    np.testing.assert_allclose(t[1:3, 2:].numpy(), a[1:3, 2:])
    idx = paddle.to_tensor(np.array([0, 2]))
    np.testing.assert_allclose(paddle.gather(t, idx, axis=0).numpy(), a[[0, 2]])
    np.testing.assert_allclose(
        paddle.index_select(t, idx, axis=1).numpy(), a[:, [0, 2]])


def test_where_and_compare():
    a = np.random.randn(3, 4).astype(np.float32)
    t = paddle.to_tensor(a)
    out = paddle.where(t > 0, t, paddle.zeros_like(t))
    np.testing.assert_allclose(out.numpy(), np.where(a > 0, a, 0))
    assert (t > 0).numpy().dtype == np.bool_


def test_topk_argsort():
    a = np.random.rand(5, 10).astype(np.float32)
    t = paddle.to_tensor(a)
    vals, idx = paddle.topk(t, 3)
    ref = np.sort(a, axis=-1)[:, ::-1][:, :3]
    np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
    order = paddle.argsort(t, descending=True)
    np.testing.assert_allclose(
        np.take_along_axis(a, order.numpy(), -1)[:, :3], ref, rtol=1e-6)


def test_cast_astype():
    t = paddle.to_tensor([1.5, 2.5])
    assert str(t.astype("int32").dtype) == "int32"
    assert str(paddle.cast(t, "float64").dtype) == "float64"


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([2], dtype="int64").numpy().sum() == 2
    np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
    assert paddle.eye(3).numpy().trace() == 3.0
    assert paddle.full([2, 2], 7.0).numpy().sum() == 28.0
    r = paddle.rand([100])
    assert 0 <= r.numpy().min() and r.numpy().max() <= 1
    assert paddle.randn([10, 10]).shape == [10, 10]
    p = paddle.randperm(10).numpy()
    assert sorted(p.tolist()) == list(range(10))


def test_linalg():
    a = np.random.rand(4, 4).astype(np.float32) + np.eye(4, dtype=np.float32) * 4
    t = paddle.to_tensor(a)
    np.testing.assert_allclose(paddle.linalg.inv(t).numpy(), np.linalg.inv(a),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(paddle.linalg.det(t).numpy(), np.linalg.det(a),
                               rtol=1e-4)
    c = paddle.linalg.cholesky(paddle.to_tensor(a @ a.T))
    np.testing.assert_allclose((c @ c.T).numpy(), a @ a.T, rtol=1e-3, atol=1e-3)


def test_einsum():
    a = np.random.rand(2, 3).astype(np.float32)
    b = np.random.rand(3, 4).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_inplace_setitem():
    t = paddle.zeros([3, 3])
    t[1, 1] = 5.0
    assert t.numpy()[1, 1] == 5.0
    t[0] = paddle.ones([3])
    np.testing.assert_allclose(t.numpy()[0], 1.0)


def test_mod_dunder():
    """Regression: _install_methods' local `mod = globals()` shadowed the
    mod() op, so Tensor % y raised TypeError('dict' not callable)."""
    x = paddle.to_tensor(np.array([5.0, 6.0], np.float32))
    np.testing.assert_allclose(np.asarray((x % 2.0).numpy()), [1.0, 0.0])
    np.testing.assert_allclose(np.asarray((7.0 % x).numpy()), [2.0, 1.0])
