"""Tensor-parallel layers: numeric parity on a dp×mp mesh + HLO collective
inspection (mirrors the reference's compile-only meta-optimizer tests and
test_collective_api_base.py column_parallel_linear_api.py payloads)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.mesh import build_mesh, mesh_guard
from paddle_tpu.nn.layer_base import functional_call, state_pytrees


@pytest.fixture
def mp_mesh():
    mesh = build_mesh({"dp": 2, "mp": 4})
    with mesh_guard(mesh):
        yield mesh


def _run_sharded(layer, x_np, mesh, x_spec=("dp",)):
    params, buffers = state_pytrees(layer)
    shardings = dist.param_sharding(layer, mesh)
    params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}

    def fwd(p, x):
        out, _ = functional_call(layer, p, (paddle.Tensor(x),),
                                 buffers=buffers)
        return out.value

    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(jnp.asarray(x_np), NamedSharding(mesh, P(*x_spec)))
    jitted = jax.jit(fwd)
    lowered = jitted.lower(params, x)
    hlo = lowered.compile().as_text()
    return np.asarray(jitted(params, x)), hlo


def test_column_parallel_linear_parity(mp_mesh):
    paddle.seed(0)
    layer = dist.ColumnParallelLinear(16, 32, gather_output=True)
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)

    ref = layer(paddle.Tensor(x)).numpy()
    out, _ = _run_sharded(layer, x, mp_mesh)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_row_parallel_linear_parity_and_collective(mp_mesh):
    paddle.seed(0)
    layer = dist.RowParallelLinear(32, 16, input_is_parallel=True)
    x = np.random.RandomState(1).randn(8, 32).astype(np.float32)

    ref = layer(paddle.Tensor(x)).numpy()
    out, hlo = _run_sharded(layer, x, mp_mesh, x_spec=("dp", "mp"))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    # partial-sum combine over mp must appear as an all-reduce (the
    # c_allreduce_sum of reference collective.py:516)
    assert "all-reduce" in hlo or "reduce-scatter" in hlo


def test_vocab_parallel_embedding_parity(mp_mesh):
    paddle.seed(0)
    layer = dist.VocabParallelEmbedding(64, 16)
    ids = np.random.RandomState(2).randint(0, 64, (4, 10))

    ref = layer(paddle.Tensor(jnp.asarray(ids))).numpy()
    out, _ = _run_sharded(layer, ids.astype(np.int32), mp_mesh)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_split_api(mp_mesh):
    paddle.seed(0)
    x = paddle.randn([4, 16])
    y = dist.split(x, (16, 24), operation="linear", axis=1, gather_out=True)
    assert y.shape == [4, 24]
    y2 = dist.split(x, (16, 24), operation="linear", axis=0)
    assert y2.shape == [4, 24]
    ids = paddle.to_tensor(np.arange(6).reshape(2, 3))
    e = dist.split(ids, (32, 8), operation="embedding")
    assert e.shape == [2, 3, 8]


def test_column_parallel_weight_is_sharded(mp_mesh):
    layer = dist.ColumnParallelLinear(16, 32, gather_output=False)
    params, _ = state_pytrees(layer)
    sh = dist.param_sharding(layer, mp_mesh)
    w = jax.device_put(params["weight"], sh["weight"])
    # out dim sharded over mp=4 → each shard holds 32/4 columns
    shard_shapes = {tuple(s.data.shape) for s in w.addressable_shards}
    assert shard_shapes == {(16, 8)}
