"""Order-statistic / scan tensor-op tail vs torch: median, quantile,
kthvalue, mode, cumprod, logcumsumexp — interpolation and tie
conventions where implementations drift."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402

rs = np.random.RandomState(59)
X = rs.randn(4, 7).astype(np.float32)


def _cmp(pd_out, t_out, atol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.numpy(), atol=atol, rtol=1e-5)


def test_median_axis_and_global():
    # paddle.median averages the two middle values on even counts
    # (reference median semantics == numpy), unlike torch's lower-median
    got = float(paddle.median(paddle.to_tensor(X)))
    assert got == pytest.approx(float(np.median(X)), abs=1e-6)
    got = paddle.median(paddle.to_tensor(X), axis=1)
    np.testing.assert_allclose(np.asarray(got.numpy()),
                               np.median(X, axis=1), atol=1e-6)


@pytest.mark.parametrize("q", [0.25, 0.5, [0.1, 0.9]])
def test_quantile_matches_torch_linear(q):
    got = paddle.quantile(paddle.to_tensor(X), q, axis=1)
    want = torch.quantile(torch.tensor(X),
                          torch.tensor(q, dtype=torch.float32), dim=1)
    if isinstance(q, list):  # torch puts q first; paddle too — compare
        assert np.asarray(got.numpy()).shape == tuple(want.shape)
    _cmp(got, want)


def test_kthvalue_and_mode():
    vals, idx = paddle.kthvalue(paddle.to_tensor(X), k=3, axis=1)
    tv, ti = torch.kthvalue(torch.tensor(X), k=3, dim=1)
    _cmp(vals, tv)
    np.testing.assert_array_equal(np.asarray(idx.numpy()), ti.numpy())
    # tie-free rows: one value strictly dominates, so mode conventions
    # (torch picks smallest on ties) cannot differ; indices too (torch
    # returns the LAST occurrence of the modal value)
    ints = np.stack([np.array([k] * 5 + [0, 1, 2, (k + 1) % 3])
                     for k in range(5)]) % 3
    mv, mi = paddle.mode(paddle.to_tensor(ints.astype(np.int64)), axis=1)
    tmv, tmi = torch.mode(torch.tensor(ints.astype(np.int64)), dim=1)
    np.testing.assert_array_equal(np.asarray(mv.numpy()), tmv.numpy())
    np.testing.assert_array_equal(np.asarray(mi.numpy()), tmi.numpy())
    # tied row: smallest most-frequent value wins, like torch
    tie = np.array([[2, 2, 0, 0, 1]], np.int64)
    mv, _ = paddle.mode(paddle.to_tensor(tie), axis=1)
    tmv, _ = torch.mode(torch.tensor(tie), dim=1)
    np.testing.assert_array_equal(np.asarray(mv.numpy()), tmv.numpy())


def test_cumprod_logcumsumexp():
    got = paddle.cumprod(paddle.to_tensor(X), dim=1)
    _cmp(got, torch.cumprod(torch.tensor(X), dim=1))
    got = paddle.logcumsumexp(paddle.to_tensor(X), axis=1)
    _cmp(got, torch.logcumsumexp(torch.tensor(X), dim=1))


def test_topk_sorted_matches():
    v, i = paddle.topk(paddle.to_tensor(X), k=3, axis=1)
    tv, ti = torch.topk(torch.tensor(X), k=3, dim=1)
    _cmp(v, tv)
    np.testing.assert_array_equal(np.asarray(i.numpy()), ti.numpy())
