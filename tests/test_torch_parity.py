"""Independent-oracle parity: paddle_tpu functional ops vs torch (CPU)
on identical inputs.  The numpy-reference OpTests share authorship bias
with the implementations; torch is an external oracle for the exact
semantics the reference op library implements (its kernels are the same
contracts torch follows: gelu erf-form, softmax, log_softmax, silu,
layer_norm epsilon placement, conv padding, smooth_l1 beta=1, kl_div
batchmean...)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402

rs = np.random.RandomState(7)


def _cmp(pd_out, t_out, atol=1e-5, rtol=1e-5):
    np.testing.assert_allclose(np.asarray(pd_out.numpy()),
                               t_out.detach().numpy(), atol=atol,
                               rtol=rtol)


@pytest.mark.parametrize("name,pd,th", [
    ("relu", F.relu, tF.relu),
    ("sigmoid", F.sigmoid, torch.sigmoid),
    ("tanh", paddle.tanh, torch.tanh),
    ("silu", F.silu, tF.silu),
    ("softplus", F.softplus, tF.softplus),
    ("softsign", F.softsign, tF.softsign),
    ("elu", F.elu, tF.elu),
    ("leaky_relu", F.leaky_relu,
     lambda t: tF.leaky_relu(t, negative_slope=0.01)),
    ("hardtanh", F.hardtanh, tF.hardtanh),
    ("relu6", F.relu6, tF.relu6),
])
def test_activation_parity(name, pd, th):
    x = rs.randn(4, 17).astype(np.float32) * 3
    _cmp(pd(paddle.to_tensor(x)), th(torch.tensor(x)))


def test_gelu_both_forms():
    x = rs.randn(3, 33).astype(np.float32) * 2
    _cmp(F.gelu(paddle.to_tensor(x)), tF.gelu(torch.tensor(x)))
    _cmp(F.gelu(paddle.to_tensor(x), approximate=True),
         tF.gelu(torch.tensor(x), approximate="tanh"), atol=1e-4)


def test_softmax_logsoftmax_parity():
    x = rs.randn(5, 11).astype(np.float32) * 4
    _cmp(F.softmax(paddle.to_tensor(x), axis=-1),
         tF.softmax(torch.tensor(x), dim=-1))
    _cmp(F.log_softmax(paddle.to_tensor(x), axis=0),
         tF.log_softmax(torch.tensor(x), dim=0))


def test_layer_norm_parity():
    x = rs.randn(4, 16).astype(np.float32)
    w = rs.rand(16).astype(np.float32) + 0.5
    b = rs.randn(16).astype(np.float32)
    got = F.layer_norm(paddle.to_tensor(x), 16, paddle.to_tensor(w),
                       paddle.to_tensor(b), epsilon=1e-5)
    want = tF.layer_norm(torch.tensor(x), (16,), torch.tensor(w),
                         torch.tensor(b), eps=1e-5)
    _cmp(got, want, atol=1e-5)


def test_conv2d_parity_padding_stride_dilation_groups():
    x = rs.randn(2, 4, 11, 9).astype(np.float32)
    w = rs.randn(8, 2, 3, 3).astype(np.float32)  # groups=2
    b = rs.randn(8).astype(np.float32)
    got = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                   paddle.to_tensor(b), stride=2, padding=1, dilation=2,
                   groups=2)
    want = tF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                     stride=2, padding=1, dilation=2, groups=2)
    _cmp(got, want, atol=1e-4)


def test_cross_entropy_parity():
    logits = rs.randn(6, 5).astype(np.float32)
    labels = rs.randint(0, 5, (6,)).astype(np.int64)
    got = F.cross_entropy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels))
    want = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels))
    _cmp(got, want)


def test_smooth_l1_and_kldiv_parity():
    a = rs.randn(4, 7).astype(np.float32)
    b = rs.randn(4, 7).astype(np.float32)
    got = F.smooth_l1_loss(paddle.to_tensor(a), paddle.to_tensor(b))
    want = tF.smooth_l1_loss(torch.tensor(a), torch.tensor(b))
    _cmp(got, want)
    p = tF.softmax(torch.tensor(a), dim=-1)
    logq = tF.log_softmax(torch.tensor(b), dim=-1)
    got = F.kl_div(paddle.to_tensor(logq.numpy()),
                   paddle.to_tensor(p.numpy()), reduction="batchmean")
    want = tF.kl_div(logq, p, reduction="batchmean")
    _cmp(got, want)


def test_max_avg_pool_parity():
    x = rs.randn(2, 3, 10, 10).astype(np.float32)
    got = F.max_pool2d(paddle.to_tensor(x), kernel_size=3, stride=2,
                       padding=1)
    want = tF.max_pool2d(torch.tensor(x), 3, stride=2, padding=1)
    _cmp(got, want)
    got = F.avg_pool2d(paddle.to_tensor(x), kernel_size=2, stride=2)
    want = tF.avg_pool2d(torch.tensor(x), 2, stride=2)
    _cmp(got, want)


def test_grad_parity_through_gelu_linear():
    """Gradients, not just forwards: d(loss)/dx for a gelu(linear) chain
    must match torch autograd."""
    x = rs.randn(3, 8).astype(np.float32)
    w = rs.randn(8, 4).astype(np.float32)

    px = paddle.to_tensor(x, stop_gradient=False)
    loss = paddle.sum(F.gelu(paddle.matmul(px, paddle.to_tensor(w))) ** 2)
    loss.backward()

    tx = torch.tensor(x, requires_grad=True)
    tloss = (tF.gelu(tx @ torch.tensor(w)) ** 2).sum()
    tloss.backward()
    np.testing.assert_allclose(np.asarray(px.grad.numpy()),
                               tx.grad.numpy(), atol=1e-4, rtol=1e-4)
