"""Request-scoped tracing, crash flight recorder, goodput ledger
(PR 12): span-tree shape through the serving stack, W3C traceparent
propagation, deterministic head sampling, flight-recorder dumps on
chaos-injected watchdog/SIGTERM exits, and the launcher-side goodput
accounting."""
import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from conftest import cpu_subprocess_env
from paddle_tpu.framework import flags as _flags
from paddle_tpu.monitor import tracing
from paddle_tpu.monitor.tracing import (NullSpan, Span, Tracer,
                                        format_traceparent,
                                        parse_traceparent, sample_decision)

pytestmark = pytest.mark.trace


@pytest.fixture()
def tracer_on():
    """Force-sample everything for the duration of one test, resetting
    the process tracer/recorder singletons on both sides."""
    import paddle_tpu.monitor as monitor

    old = _flags.flag("FLAGS_trace_sample_rate")
    _flags.set_flags({"FLAGS_trace_sample_rate": 1.0})
    monitor.reset()
    yield tracing.default_tracer()
    _flags.set_flags({"FLAGS_trace_sample_rate": old})
    monitor.reset()


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------
class TestTracerCore:
    def test_traceparent_roundtrip(self):
        tid, sid = "ab" * 16, "cd" * 8
        hdr = format_traceparent(tid, sid, True)
        assert hdr == f"00-{tid}-{sid}-01"
        assert parse_traceparent(hdr) == (tid, sid, True)
        assert parse_traceparent(format_traceparent(tid, sid, False)) \
            == (tid, sid, False)
        # malformed headers are rejected, not half-parsed
        for bad in ("", "00-xyz", f"00-{tid}-{sid}", f"00-{'0'*32}-{sid}-01",
                    f"00-{tid}-{'0'*16}-01", "zz-" + hdr[3:]):
            assert parse_traceparent(bad) is None, bad

    def test_sampling_determinism(self):
        lo = "00000000" + "a" * 24   # prefix 0 -> always sampled
        hi = "ffffffff" + "a" * 24   # prefix max -> sampled only at 1.0
        assert sample_decision(lo, 0.01) is True
        assert sample_decision(hi, 0.99) is False
        assert sample_decision(hi, 1.0) is True
        mid = "80000000" + "a" * 24  # exactly 0.5 of the id space
        assert sample_decision(mid, 0.5) is False
        assert sample_decision(mid, 0.51) is True
        # the decision is a pure function of (trace_id, rate): client and
        # server reach the same verdict with no coordination
        for rate in (0.0, 0.25, 0.5, 1.0):
            for tid in (lo, hi, mid):
                assert sample_decision(tid, rate) \
                    == sample_decision(tid, rate)

    def test_span_tree_and_ring_bound(self):
        tr = Tracer(sample_rate=1.0, max_spans=5)
        with tr.start_span("root", attrs={"k": 1}) as root:
            child = root.child("child", x=2)
            child.event("tick", i=0)
            child.end(status="ok")
        spans = tr.spans()
        assert [s["name"] for s in spans] == ["child", "root"]
        c, r = spans
        assert c["trace_id"] == r["trace_id"]
        assert c["parent_id"] == r["span_id"]
        assert c["attrs"]["status"] == "ok" and c["attrs"]["x"] == 2
        assert c["events"][0]["name"] == "tick"
        # bounded ring: only the newest max_spans survive
        for i in range(12):
            tr.start_span(f"s{i}").end()
        assert len(tr.spans()) == 5
        assert tr.spans()[-1]["name"] == "s11"

    def test_unsampled_paths(self):
        assert not Tracer(sample_rate=0.0).enabled
        assert isinstance(Tracer(sample_rate=0.0).start_span("x"), NullSpan)
        tr = Tracer(sample_rate=1.0, max_spans=16)
        # an incoming UNsampled traceparent wins over the local rate
        hdr = format_traceparent("ab" * 16, "cd" * 8, False)
        sp = tr.start_span("x", traceparent=hdr)
        assert isinstance(sp, NullSpan) and not sp.sampled
        # ...and still propagates trace identity downstream (flag 00)
        assert sp.traceparent is not None
        assert sp.traceparent.startswith("00-" + "ab" * 16)
        assert sp.traceparent.endswith("-00")
        sp.event("ignored")
        assert sp.child("y") is sp
        sp.end()
        assert tr.spans() == []
        # an incoming SAMPLED traceparent is adopted
        sp2 = tr.start_span("x", traceparent=format_traceparent(
            "ef" * 16, "12" * 8, True))
        assert isinstance(sp2, Span)
        assert sp2.trace_id == "ef" * 16 and sp2.parent_id == "12" * 8
        sp2.end()

    def test_chrome_trace_export(self):
        tr = Tracer(sample_rate=1.0, max_spans=16)
        with tr.start_span("req") as root:
            ch = root.child("phase")
            ch.event("tok")
            ch.end()
        doc = tr.chrome_trace()
        evts = doc["traceEvents"]
        kinds = {e["ph"] for e in evts}
        assert kinds == {"X", "i"}
        xs = [e for e in evts if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"req", "phase"}
        for e in xs:
            assert e["dur"] >= 0 and "ts" in e and "pid" in e
        # perfetto-loadable == valid JSON document
        json.loads(json.dumps(doc))


# ---------------------------------------------------------------------------
# serving span trees (client -> server -> generation engine)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def gen_server():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.generation import GenerationEngine
    from paddle_tpu.serving.server import ServingServer

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=64, dropout=0.0,
                    attn_dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = GenerationEngine(model, max_slots=2, max_seq_len=32,
                           prompt_buckets="8")
    srv = ServingServer(None, gen_engine=eng,
                        install_signal_handlers=False).start()
    yield srv
    srv.shutdown()


class TestServingTrace:
    def _tree(self, tracer, trace_id, want=()):
        """Spans by name; polls briefly until `want` names appear — the
        server exports its span AFTER the response body is flushed, so
        the client can observe completion before the tree is whole
        (visible on the streaming path, where the final SSE chunk
        precedes the handler return)."""
        deadline = time.monotonic() + 2.0
        while True:
            by = {s["name"]: s for s in tracer.spans(trace_id=trace_id)}
            if set(want) <= set(by) or time.monotonic() > deadline:
                return by
            time.sleep(0.02)

    def test_blocking_generate_tree(self, tracer_on, gen_server):
        from paddle_tpu.serving.client import ServingClient

        client = ServingClient(gen_server.url)
        out = client.generate([1, 2, 3, 4], max_new_tokens=5)
        assert len(out["tokens"]) >= 1
        trace_id = client.last_traceparent.split("-")[1]
        by = self._tree(tracer_on, trace_id,
                        want=("client.generate", "server.generate",
                              "gen.queued", "gen.prefill", "gen.decode"))
        assert {"client.generate", "server.generate", "gen.queued",
                "gen.prefill", "gen.decode"} <= set(by)
        # parentage: engine children hang off the server span, which
        # hangs off the client root
        assert by["server.generate"]["parent_id"] \
            == by["client.generate"]["span_id"]
        for child in ("gen.queued", "gen.prefill", "gen.decode"):
            assert by[child]["parent_id"] == by["server.generate"]["span_id"]
        # ttft decomposition: the queue/prefill/decode children are all
        # inside (and together bounded by) the request wall time
        total = sum(by[c]["dur_ms"] for c in
                    ("gen.queued", "gen.prefill", "gen.decode"))
        assert 0 < total <= by["client.generate"]["dur_ms"] * 1.05
        assert by["gen.decode"]["events"], "per-token events missing"
        assert by["server.generate"]["attrs"]["tokens"] == 5

    def test_streaming_generate_tree(self, tracer_on, gen_server):
        from paddle_tpu.serving.client import ServingClient

        client = ServingClient(gen_server.url)
        events = list(client.generate_stream([5, 6, 7], max_new_tokens=4))
        assert events[-1].get("done")
        trace_id = client.last_traceparent.split("-")[1]
        by = self._tree(tracer_on, trace_id,
                        want=("client.generate_stream", "server.generate",
                              "gen.queued", "gen.prefill", "gen.decode"))
        assert {"client.generate_stream", "server.generate", "gen.queued",
                "gen.prefill", "gen.decode"} <= set(by)
        ntok = sum(1 for e in events if "token" in e)
        assert by["client.generate_stream"]["attrs"]["tokens"] == ntok
        assert [e["name"] for e in
                by["client.generate_stream"]["events"]] == ["first_token"]

    def test_explicit_traceparent_joins_trace(self, tracer_on, gen_server):
        from paddle_tpu.serving.client import ServingClient

        tid = "ab" * 16
        hdr = format_traceparent(tid, "cd" * 8, True)
        client = ServingClient(gen_server.url)
        client.generate([9, 8, 7], max_new_tokens=2, traceparent=hdr)
        assert client.last_traceparent == hdr  # forwarded as-is
        by = self._tree(tracer_on, tid,
                        want=("server.generate", "gen.queued",
                              "gen.prefill", "gen.decode"))
        # no client-side root: the caller owns that span; the server
        # adopted the incoming identity for its whole subtree
        assert "client.generate" not in by
        assert by["server.generate"]["parent_id"] == "cd" * 8
        assert {"gen.queued", "gen.prefill", "gen.decode"} <= set(by)

    def test_unsampled_rate_produces_no_spans(self, gen_server):
        import paddle_tpu.monitor as monitor
        from paddle_tpu.serving.client import ServingClient

        old = _flags.flag("FLAGS_trace_sample_rate")
        _flags.set_flags({"FLAGS_trace_sample_rate": 0.0})
        monitor.reset()
        try:
            client = ServingClient(gen_server.url)
            out = client.generate([1, 2, 3], max_new_tokens=2)
            assert len(out["tokens"]) >= 1
            assert client.last_traceparent is None
            assert tracing.default_tracer().spans() == []
        finally:
            _flags.set_flags({"FLAGS_trace_sample_rate": old})
            monitor.reset()

    def test_healthz_enriched(self, gen_server):
        from paddle_tpu.serving.client import ServingClient

        h = ServingClient(gen_server.url).healthz()
        assert h["status"] == "ok" and h["pid"] == os.getpid()
        assert h["device_count"] >= 1 and "jax_version" in h
        assert "version" in h and h["uptime_s"] >= 0.0


# ---------------------------------------------------------------------------
# /debug/spans endpoint
# ---------------------------------------------------------------------------
class TestDebugSpans:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=5) as r:
            return json.loads(r.read())

    def test_endpoint_json_and_chrome(self, tracer_on):
        from paddle_tpu.monitor import MonitorServer

        with tracer_on.start_span("req") as root:
            root.child("phase").end()
        with MonitorServer(port=0) as srv:
            doc = self._get(srv.url + "/debug/spans")
            assert doc["sample_rate"] == 1.0
            assert doc["count"] == len(doc["spans"]) == 2
            tid = doc["spans"][0]["trace_id"]
            one = self._get(f"{srv.url}/debug/spans?trace_id={tid}&limit=1")
            assert one["count"] == 1
            chrome = self._get(srv.url + "/debug/spans?format=chrome")
            assert {e["ph"] for e in chrome["traceEvents"]} == {"X"}
            h = self._get(srv.url + "/healthz")
            assert h["pid"] == os.getpid() and "version" in h


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_bound_and_dump(self, tmp_path):
        from paddle_tpu.monitor.flightrec import FlightRecorder

        rec = FlightRecorder(directory=str(tmp_path), max_records=5)
        for i in range(12):
            rec.record("tick", i=i)
        assert len(rec) == 5
        assert [r["i"] for r in rec.records("tick")] == list(range(7, 12))
        path = rec.dump("test", extra={"note": "x"})
        doc = json.loads(open(path).read())
        assert doc["version"] == 1 and doc["reason"] == "test"
        assert doc["pid"] == os.getpid() and doc["note"] == "x"
        assert len(doc["records"]) == 5
        assert set(doc["accounting"]) == {"wall_s", "train_s", "compile_s",
                                          "ckpt_stall_s"}
        assert rec.dumped_reason == "test"

    def test_span_listener_mirrors_into_ring(self, tmp_path):
        from paddle_tpu.monitor.flightrec import FlightRecorder

        rec = FlightRecorder(directory=str(tmp_path), max_records=8)
        tr = Tracer(sample_rate=1.0, max_spans=8)
        tr.add_listener(rec.on_span)
        tr.start_span("serve.request", attrs={"a": 1}).end(status="ok")
        spans = rec.records("span")
        assert len(spans) == 1
        assert spans[0]["name"] == "serve.request"
        assert spans[0]["attrs"]["status"] == "ok"

    def _run_trainer(self, tmp_path, chaos_env, watchdog=None,
                     timeout=120):
        script = f"""
import sys
from paddle_tpu.monitor import flightrec
from paddle_tpu.distributed.resilience import ResilientRunner

flightrec.configure({str(tmp_path)!r})
flightrec.install_hooks()

def step(i, s):
    flightrec.record("step", step=i)
    return s, 0.1

runner = ResilientRunner(watchdog_timeout={watchdog!r})
runner.run(step, {{}}, num_steps=10)
"""
        env = cpu_subprocess_env()
        env.update(chaos_env)
        return subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True,
                              timeout=timeout)

    def _the_dump(self, tmp_path):
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("flightrec-") and p.endswith(".json")]
        assert len(dumps) == 1, dumps
        return json.loads(open(os.path.join(tmp_path, dumps[0])).read())

    @pytest.mark.chaos
    def test_watchdog_exit_86_leaves_dump(self, tmp_path):
        r = self._run_trainer(
            tmp_path, {"PADDLE_CHAOS_SLOW_STEP": "3",
                       "PADDLE_CHAOS_SLOW_SECONDS": "30"}, watchdog=0.5)
        assert r.returncode == 86, r.stderr[-2000:]
        doc = self._the_dump(tmp_path)
        assert doc["reason"] == "watchdog"
        # the ring shows training progressed up to the stalled step
        # (chaos stalls at the step-3 boundary, before step_fn runs)
        assert [x["step"] for x in doc["records"]
                if x["kind"] == "step"] == [1, 2]
        assert any(x["kind"] == "watchdog" for x in doc["records"])

    @pytest.mark.chaos
    def test_sigterm_preemption_leaves_dump(self, tmp_path):
        r = self._run_trainer(
            tmp_path, {"PADDLE_CHAOS_PREEMPT_STEP": "2"}, watchdog=None)
        assert r.returncode == 75, r.stderr[-2000:]
        doc = self._the_dump(tmp_path)
        assert doc["reason"] == "preempt"
        assert any(x["kind"] == "preempt" for x in doc["records"])


# ---------------------------------------------------------------------------
# goodput ledger
# ---------------------------------------------------------------------------
class TestGoodputLedger:
    def _dump(self, d, name, train, compile_s=0.0, stall=0.0):
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, name), "w") as f:
            json.dump({"accounting": {
                "wall_s": train + compile_s + stall, "train_s": train,
                "compile_s": compile_s, "ckpt_stall_s": stall}}, f)

    def test_aggregation_and_ratio(self, tmp_path):
        from paddle_tpu.distributed.goodput import GoodputLedger
        from paddle_tpu.utils.metrics import MetricsRegistry

        self._dump(str(tmp_path / "rank0"), "flightrec-11.json",
                   train=6.0, compile_s=2.0, stall=1.0)
        self._dump(str(tmp_path / "rank1"), "flightrec-22.json", train=3.0)
        reg = MetricsRegistry()
        led = GoodputLedger(str(tmp_path), registry=reg)
        led.add_backoff(2.0)
        led.add_down(1.0)
        t = led.publish()
        assert t == {"productive_train": 9.0, "compile": 2.0,
                     "ckpt_stall": 1.0, "restart_backoff": 2.0,
                     "down": 1.0}
        assert abs(led.ratio() - 9.0 / 15.0) < 1e-9
        # re-publish must not double-count (path+mtime keyed)
        led.publish()
        assert led.totals()["productive_train"] == 9.0
        text = reg.prometheus_text()
        assert 'paddle_badput_seconds_total{reason="compile"} 2' in text
        assert "paddle_goodput_ratio" in text

    def test_jsonl_fallback_for_sigkilled_rank(self, tmp_path):
        from paddle_tpu.distributed.goodput import GoodputLedger

        # rank0 dumped; rank1 was SIGKILLed — only its event log remains
        self._dump(str(tmp_path / "rank0"), "flightrec-11.json", train=4.0)
        os.makedirs(tmp_path / "rank1")
        with open(tmp_path / "rank1" / "events.jsonl", "w") as f:
            f.write(json.dumps({"event": "fit_begin"}) + "\n")
            f.write(json.dumps({"event": "window", "wall_s": 2.5}) + "\n")
            f.write(json.dumps({"event": "window", "wall_s": 1.5}) + "\n")
        led = GoodputLedger(str(tmp_path))
        led.ingest()
        assert led.totals()["productive_train"] == 8.0
        # a dump appearing later SUPERSEDES nothing (separate files), but
        # a rank dir WITH a dump never double-reads its JSONL
        self._dump(str(tmp_path / "rank1"), "flightrec-22.json", train=5.0)
        led2 = GoodputLedger(str(tmp_path))
        led2.ingest()
        assert led2.totals()["productive_train"] == 9.0

    def test_counter_stays_monotonic(self, tmp_path):
        from paddle_tpu.distributed.goodput import GoodputLedger
        from paddle_tpu.utils.metrics import MetricsRegistry

        reg = MetricsRegistry()
        led = GoodputLedger(str(tmp_path), registry=reg)
        led.add_backoff(1.5)
        led.publish()
        c = reg.get("paddle_badput_seconds_total")
        assert c.get("restart_backoff") == 1.5
        led.publish()   # no growth -> no increment
        assert c.get("restart_backoff") == 1.5
        led.add_backoff(0.5)
        led.publish()
        assert c.get("restart_backoff") == 2.0


# ---------------------------------------------------------------------------
# training spans (fit bridged through the tracer)
# ---------------------------------------------------------------------------
class TestTrainingSpans:
    def test_fit_emits_span_tree(self, tracer_on, tmp_path):
        import paddle_tpu as paddle

        _flags.set_flags({"FLAGS_telemetry_dir": str(tmp_path)})
        import paddle_tpu.monitor as monitor
        monitor.reset()
        try:
            net = paddle.nn.Linear(4, 2)
            model = paddle.Model(net)
            model.prepare(
                paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters()),
                paddle.nn.CrossEntropyLoss())
            x = np.random.randn(16, 4).astype("float32")
            y = np.random.randint(0, 2, (16, 1))
            ds = paddle.io.TensorDataset([x, y])
            model.fit(ds, batch_size=8, epochs=2, verbose=0)
            spans = tracing.default_tracer().spans()
            names = [s["name"] for s in spans]
            assert names.count("train.fit") == 1
            assert names.count("train.epoch") == 2
            assert names.count("train.step") == 4
            fit = next(s for s in spans if s["name"] == "train.fit")
            assert fit["attrs"]["status"] == "ok"
            assert fit["attrs"]["it"] == 4
            for s in spans:
                if s["name"] != "train.fit":
                    assert s["trace_id"] == fit["trace_id"]
            # spans mirrored into the flight-recorder ring
            from paddle_tpu.monitor import flightrec
            rec = flightrec.get_recorder()
            assert rec is not None
            assert any(r["name"] == "train.fit"
                       for r in rec.records("span"))
        finally:
            _flags.set_flags({"FLAGS_telemetry_dir": ""})
            monitor.reset()
