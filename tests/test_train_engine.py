"""Device-resident async training engine (hapi/engine.py).

Pins the three contracts the engine introduces:
  * sync-free stepping — no hidden device→host transfer in the fit step
    path outside the explicit `host_fetch()` scopes (loss-ring drains,
    metric updates, checkpoint materialization).  The CPU backend is
    zero-copy so jax's transfer guard never fires there; the test
    patches the jax array host-conversion hooks instead and keeps the
    transfer guard armed for real-accelerator runs.
  * donation correctness — fitted params/opt-state after N steps through
    the donated engine are bitwise-identical to the legacy non-donated
    `train_batch` loop.
  * persistent compilation cache — FLAGS_jit_cache_dir makes a second
    PROCESS skip XLA compilation (perf marker; run via
    tools/perf_smoke.sh).
"""
import json
import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import transfer
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.engine import TrainEngine
from paddle_tpu.io import DataLoader, TensorDataset

from conftest import cpu_subprocess_env


def _model_and_data(n=24):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    rs = np.random.RandomState(0)
    x = rs.randn(n, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    # numpy-backed dataset: the data path stays host-side, so the ONLY
    # legitimate device→host traffic in fit() is the engine's explicit
    # loss-ring drain
    ds = TensorDataset([x, y])
    model = Model(net)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=0.01,
                              parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss())
    return model, ds


def _weights(model):
    return {k: np.asarray(p._value)
            for k, p in model.network.named_parameters()}


class _SyncTripwire:
    """Fails the test on ANY jax-array host conversion outside a
    sanctioned transfer.host_fetch() scope."""

    HOOKS = ("__array__", "__float__", "__int__", "__bool__", "__index__",
             "block_until_ready")

    def __init__(self):
        from jax._src.array import ArrayImpl
        self.cls = ArrayImpl
        self.orig = {}
        self.sanctioned_calls = 0

    def __enter__(self):
        for name in self.HOOKS:
            orig = getattr(self.cls, name)
            self.orig[name] = orig

            def hook(arr, *a, _orig=orig, _name=name, **kw):
                if not transfer.in_host_fetch():
                    raise AssertionError(
                        f"hidden device→host sync: ArrayImpl.{_name} "
                        "called outside host_fetch() in the fit step path")
                self.sanctioned_calls += 1
                return _orig(arr, *a, **kw)

            setattr(self.cls, name, hook)
        return self

    def __exit__(self, *exc):
        for name, orig in self.orig.items():
            setattr(self.cls, name, orig)
        return False


class TestSyncFreeStepping:
    def test_fit_no_hidden_host_sync_in_step_path(self):
        """3+ train steps with the transfer guard armed AND the array
        host-conversion hooks tripwired: only the explicit log-interval
        fetch (and epoch-end drain) may touch the host."""
        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0)
        with _SyncTripwire() as wire:
            with jax.transfer_guard_device_to_host("disallow"):
                model.fit(ds, batch_size=8, epochs=1, shuffle=False,
                          verbose=0, log_freq=100)
        # the sanctioned drains DID happen (the tripwire saw them inside
        # host_fetch) — the loop is sync-free, not fetch-free
        assert wire.sanctioned_calls > 0

    def test_tripwire_catches_real_sync(self):
        """Meta-test: the tripwire actually fires on an unsanctioned
        host read (guards against the test going vacuous)."""
        import jax.numpy as jnp

        x = jax.jit(lambda a: a + 1)(jnp.zeros(()))
        with _SyncTripwire():
            with pytest.raises(AssertionError, match="hidden"):
                float(x)

    def test_loss_history_matches_eager_values(self):
        """Deferred (ring-drained) losses are the same scalars the eager
        per-step fetch would have produced."""
        ma, ds = _model_and_data()
        ha = ma.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
                    log_freq=1)        # drains every step
        mb, ds = _model_and_data()
        hb = mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
                    log_freq=0)        # drains only at epoch end
        np.testing.assert_array_equal(ha["loss"], hb["loss"])


class TestDonationCorrectness:
    def test_engine_bitwise_matches_eager_train_batch(self):
        """The donated, device-resident fit path reproduces the legacy
        non-donated train_batch loop bit for bit (params AND opt
        slots)."""
        ma, ds = _model_and_data()
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        for _ in range(2):
            ma.network.train()
            for batch in loader:
                inputs, labels = ma._split_batch(list(batch))
                ma.train_batch(inputs, labels)
        ref_w = _weights(ma)

        mb, ds = _model_and_data()
        mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0)
        got_w = _weights(mb)

        assert set(ref_w) == set(got_w)
        for k in ref_w:
            np.testing.assert_array_equal(got_w[k], ref_w[k], err_msg=k)
        ref_o = jax.tree_util.tree_leaves(ma._opt_state)
        got_o = jax.tree_util.tree_leaves(mb._opt_state)
        assert len(ref_o) == len(got_o)
        for a, b in zip(ref_o, got_o):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert ma._optimizer._step_count == mb._optimizer._step_count

    def test_write_back_then_train_batch_continues(self):
        """After fit() the Layer tree + opt state are the single source
        of truth again: a train_batch call picks up seamlessly."""
        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0)
        before = _weights(model)
        steps_before = model._optimizer._step_count
        rs = np.random.RandomState(1)
        model.train_batch(
            [paddle.to_tensor(rs.randn(8, 4).astype("float32"))],
            [paddle.to_tensor(rs.randint(0, 2, (8,)).astype("int64"))])
        after = _weights(model)
        assert model._optimizer._step_count == steps_before + 1
        assert any(not np.array_equal(before[k], after[k]) for k in before)

    def test_mid_fit_layer_values_stay_valid(self):
        """Epoch-boundary write-back hands the Layer tree device COPIES:
        a user callback reading params between epochs must never see a
        donated (invalidated) buffer."""
        from paddle_tpu.hapi.callbacks import Callback

        seen = []

        class Peek(Callback):
            def on_epoch_end(self, epoch, logs=None):
                seen.append({k: np.asarray(p._value) for k, p in
                             self.model.network.named_parameters()})

        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=3, shuffle=False, verbose=0,
                  callbacks=[Peek()])
        assert len(seen) == 3
        # epochs progressed → the snapshots differ
        assert any(not np.array_equal(seen[0][k], seen[2][k])
                   for k in seen[0])

    def test_epoch_end_callback_weight_mutation_honored(self):
        """param.set_value from an epoch-end callback must fold back
        into the device-resident state — next epoch trains from the
        mutated weights, bitwise-equal to the eager oracle."""
        from paddle_tpu.hapi.callbacks import Callback

        def mutate(net):
            for _, p in net.named_parameters():
                p.set_value(np.zeros(p.shape, np.float32))

        # oracle: eager train_batch loop with the same mutation between
        # epochs
        ma, ds = _model_and_data()
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        for epoch in range(2):
            ma.network.train()
            for batch in loader:
                inputs, labels = ma._split_batch(list(batch))
                ma.train_batch(inputs, labels)
            if epoch == 0:
                mutate(ma.network)
        ref = _weights(ma)

        class Mutator(Callback):
            def on_epoch_end(self, epoch, logs=None):
                if epoch == 0:
                    mutate(self.model.network)

        mb, ds = _model_and_data()
        mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
               callbacks=[Mutator()])
        got = _weights(mb)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    def test_per_batch_weight_clip_callback_honored(self):
        """WGAN-style per-batch weight clipping via a user callback
        matches the eager loop bit for bit (user callbacks trigger the
        per-batch dirty scan)."""
        from paddle_tpu.hapi.callbacks import Callback

        def clip(net):
            for _, p in net.named_parameters():
                p.set_value(np.clip(np.asarray(p._value), -0.05, 0.05)
                            .astype(np.float32))

        ma, ds = _model_and_data()
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        ma.network.train()
        for batch in loader:
            inputs, labels = ma._split_batch(list(batch))
            ma.train_batch(inputs, labels)
            clip(ma.network)
        ref = _weights(ma)

        class Clipper(Callback):
            def on_train_batch_end(self, step, logs=None):
                clip(self.model.network)

        mb, ds = _model_and_data()
        mb.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
               callbacks=[Clipper()])
        got = _weights(mb)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    def test_lr_scheduler_refreshes_device_lr(self):
        """A host-side LRScheduler still drives the donated step: the lr
        leaf is refreshed when the scheduler advances."""
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 4),
                                   paddle.nn.Linear(4, 2))
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                              step_size=2, gamma=0.5)
        model = Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=sched,
                                           parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss())
        rs = np.random.RandomState(0)
        ds = TensorDataset([rs.randn(16, 4).astype("float32"),
                            rs.randint(0, 2, (16,)).astype("int64")])
        model.fit(ds, batch_size=4, epochs=1, shuffle=False, verbose=0)
        # 4 steps, decay every 2: steps ran at lr 0.1,0.1,0.05,0.05 — the
        # engine's device lr followed the host scheduler down to 0.05;
        # the callback steps the scheduler once more AFTER the last batch
        assert model._engine._lr_host == pytest.approx(0.05)
        assert model._optimizer.get_lr() == pytest.approx(0.025)


class TestPredictBatch:
    def test_predict_batch_reuses_cached_eval_fn(self):
        model, ds = _model_and_data()
        x = paddle.to_tensor(np.zeros((4, 4), np.float32))
        out1 = model.predict_batch([x])
        fn = model._eval_fn
        assert fn is not None
        out2 = model.predict_batch([x])
        assert model._eval_fn is fn  # cached, not rebuilt
        np.testing.assert_array_equal(np.asarray(out1.numpy()),
                                      np.asarray(out2.numpy()))


class TestPersistentCompileCache:
    def test_flag_round_trip(self, tmp_path):
        from paddle_tpu.framework import flags as F

        old = F.flag("FLAGS_jit_cache_dir")
        try:
            paddle.set_flags({"FLAGS_jit_cache_dir": str(tmp_path)})
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
            paddle.set_flags({"FLAGS_jit_cache_dir": ""})
            assert jax.config.jax_compilation_cache_dir is None
        finally:
            paddle.set_flags({"FLAGS_jit_cache_dir": old})

    @pytest.mark.perf
    @pytest.mark.slow
    def test_second_process_compiles_faster(self, tmp_path):
        """Two identical processes compile the same train step; the
        second must hit FLAGS_jit_cache_dir and compile measurably
        faster (the `decode_first_call_seconds: 1.7` tax in BENCH is
        exactly this, paid once per process without the cache)."""
        script = tmp_path / "compile_probe.py"
        script.write_text(textwrap.dedent("""
            import json, time
            import paddle_tpu as paddle  # applies FLAGS_jit_cache_dir
            import jax
            import jax.numpy as jnp
            from paddle_tpu.nn.layer_base import functional_call, \\
                state_pytrees

            paddle.seed(0)
            net = paddle.nn.Sequential(*[paddle.nn.Linear(128, 128)
                                         for _ in range(6)])
            params, buffers = state_pytrees(net)
            opt = paddle.optimizer.Adam(learning_rate=1e-3)
            opt_state = opt.init_pytree(params)

            def step(p, s, x):
                def loss(p):
                    out, _ = functional_call(net, p,
                                             (paddle.Tensor(x),),
                                             buffers=buffers)
                    return jnp.mean(out.value ** 2)
                l, g = jax.value_and_grad(loss)(p)
                p, s = opt.apply_pytree(p, g, s, lr=1e-3, step=1)
                return p, s, l

            x = jnp.zeros((32, 128), jnp.float32)
            t0 = time.perf_counter()
            jax.jit(step).lower(params, opt_state, x).compile()
            print(json.dumps(
                {"compile_s": time.perf_counter() - t0}))
        """))
        env = cpu_subprocess_env()
        env["FLAGS_JIT_CACHE_DIR"] = str(tmp_path / "xla-cache")
        env["FLAGS_JIT_CACHE_MIN_COMPILE_SECS"] = "0"

        def run():
            r = subprocess.run([sys.executable, str(script)], env=env,
                               capture_output=True, text=True, timeout=300)
            assert r.returncode == 0, r.stderr[-2000:]
            return json.loads(r.stdout.strip().splitlines()[-1])["compile_s"]

        first = run()
        assert os.listdir(tmp_path / "xla-cache"), \
            "persistent cache wrote no entries"
        second = run()
        assert second < first, (first, second)
        assert second < first * 0.7, \
            f"cache hit barely helped: {first:.2f}s -> {second:.2f}s"


class TestStepTimers:
    def test_fit_records_phase_timings(self):
        model, ds = _model_and_data()
        model.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0)
        s = model._last_fit_timers.summary()
        assert {"data", "dispatch", "sync"} <= set(s)
        assert s["dispatch"]["count"] == 3  # 24 samples / batch 8
        for phase in ("data", "dispatch", "sync"):
            assert s[phase]["total_s"] >= 0.0


class TestEngineUnit:
    def test_begin_requires_prepare(self):
        model = Model(paddle.nn.Linear(2, 2))
        with pytest.raises(RuntimeError, match="prepare"):
            TrainEngine(model).begin()

    def test_state_is_donation_safe_copy(self):
        """begin() snapshots COPIES: donating the engine state must never
        invalidate the arrays the Layer tree holds."""
        model, ds = _model_and_data()
        eng = TrainEngine(model).begin()
        layer_vals = _weights(model)
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(8, 4).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (8,)).astype("int64"))
        eng.step([x], [y])   # donates the begin() snapshot
        # layer arrays still readable and unchanged
        for k, v in _weights(model).items():
            np.testing.assert_array_equal(v, layer_vals[k])
        assert eng.drain()

    def test_finish_drops_poisoned_state(self):
        """A dispatch that failed AFTER donating leaves deleted buffers
        in the engine; finish() must drop them instead of clobbering the
        valid Layer-tree weights."""
        model, ds = _model_and_data()
        eng = TrainEngine(model).begin()
        layer_vals = _weights(model)
        for v in eng.state["trainable"].values():
            v.delete()   # what a failed donated dispatch leaves behind
        eng.finish()
        assert not eng.active
        for k, v in _weights(model).items():  # weights survived intact
            np.testing.assert_array_equal(v, layer_vals[k])
