"""utils tail modules (reference python/paddle/utils/): install_check
run_check, op_version checkpoint queries, image_util preprocessing."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import image_util as iu


def test_run_check_passes_and_reports(capsys):
    assert paddle.utils.run_check() is True
    out = capsys.readouterr().out
    assert "installed successfully" in out
    # conftest forces an 8-device CPU mesh, so the dp tier must run too
    assert "works well on 8 devices" in out


def test_op_version_checker_singleton_and_defaults():
    a = paddle.utils.OpLastCheckpointChecker()
    b = paddle.utils.OpLastCheckpointChecker()
    assert a is b
    assert a.get_version("roi_align") >= 1
    assert a.get_version("not_an_op") == 0
    assert a.check_upgrade("roi_align", 1)
    assert not a.check_upgrade("not_an_op", 1)
    assert "pixel" in a.get_note("roi_align")


def test_resize_keeps_aspect_short_side():
    im = np.zeros((20, 30, 3), np.uint8)
    out = iu.resize_image(im, 10)
    assert out.shape == (10, 15, 3)   # short side -> 10, aspect kept
    out = iu.resize_image(np.zeros((40, 20, 3), np.uint8), 10)
    assert out.shape == (20, 10, 3)


def test_flip_is_involution():
    im = np.random.RandomState(0).randint(0, 255, (6, 8, 3), np.uint8)
    np.testing.assert_array_equal(iu.flip(iu.flip(im)), im)
    np.testing.assert_array_equal(iu.flip(im), im[:, ::-1])


def test_center_crop_and_seeded_random_crop():
    im = np.arange(10 * 10).reshape(10, 10).astype(np.float32)
    c = iu.crop_img(im, 4, test=True)
    assert c.shape == (4, 4)
    np.testing.assert_array_equal(c, im[3:7, 3:7])
    paddle.seed(5)
    r1 = iu.crop_img(im, 4, test=False)
    paddle.seed(5)
    r2 = iu.crop_img(im, 4, test=False)
    np.testing.assert_array_equal(r1, r2)  # paddle.seed reproduces


def test_preprocess_img_mean_and_layout():
    im = np.full((8, 8, 3), 10.0, np.float32)
    v = iu.preprocess_img(im, img_mean=[1.0, 2.0, 3.0], crop_size=4,
                          is_train=False)
    assert v.shape == (3 * 4 * 4,)
    np.testing.assert_allclose(v[:16], 9.0)    # channel 0: 10 - 1
    np.testing.assert_allclose(v[-16:], 7.0)   # channel 2: 10 - 3


def test_flattened_chw_vector_accepted_and_bounds_raise():
    """Reference scripts pass flattened square CHW float vectors; and
    undersized images / mismatched means must raise, not silently
    mis-shape."""
    sq = np.arange(3 * 6 * 6, dtype=np.float32)  # flattened 3x6x6 CHW
    c = iu.crop_img(sq, 4, color=True, test=True)
    assert c.shape == (4, 4, 3)
    with pytest.raises(ValueError, match="smaller than crop"):
        iu.crop_img(np.zeros((3, 3)), 4)
    with pytest.raises(ValueError, match="smaller than crop"):
        iu.oversample(np.zeros((5, 5, 3)), (8, 8))
    with pytest.raises(ValueError, match="img_mean"):
        iu.preprocess_img(np.zeros((8, 8, 3)), img_mean=np.zeros(7),
                          crop_size=4, is_train=False)
    a = paddle.utils.OpLastCheckpointChecker()
    assert a.check_modified("adam") == [] and a.check_bugfix("adam") == []


def test_oversample_ten_crops():
    im = np.random.RandomState(1).rand(12, 12, 3).astype(np.float32)
    crops = iu.oversample(im, (8, 8))
    assert crops.shape == (10, 8, 8, 3)
    # 5 views + their mirrors
    np.testing.assert_array_equal(crops[5], crops[0][:, ::-1])


def test_image_transformer_pipeline():
    im = np.random.RandomState(2).rand(5, 6, 3).astype(np.float32)
    t = iu.ImageTransformer(transpose=(2, 0, 1), channel_swap=(2, 1, 0),
                            mean=np.array([1.0, 2.0, 3.0]))
    out = t.transform(im)
    assert out.shape == (3, 5, 6)
    want = np.transpose(im[:, :, [2, 1, 0]], (2, 0, 1)) \
        - np.array([1.0, 2.0, 3.0]).reshape(-1, 1, 1)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_load_image_and_decode_jpeg(tmp_path):
    pytest.importorskip("PIL")
    from PIL import Image

    arr = np.random.RandomState(3).randint(0, 255, (9, 7, 3), np.uint8)
    p = tmp_path / "x.png"
    Image.fromarray(arr).save(p)
    loaded = iu.load_image(str(p))
    np.testing.assert_array_equal(loaded, arr)
    import io as _io

    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=95)
    dec = iu.decode_jpeg(buf.getvalue())
    assert dec.shape == arr.shape
