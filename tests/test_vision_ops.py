"""Detection op tests (paddle.vision.ops vs numpy references — the
detection/ op-family slice of the OpTest contract)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def rs(seed=0):
    return np.random.RandomState(seed)


class TestBoxHelpers:
    def test_box_area_iou(self):
        a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
        area = np.asarray(V.box_area(a).numpy())
        np.testing.assert_allclose(area, [4, 4])
        iou = np.asarray(V.box_iou(a, a).numpy())
        np.testing.assert_allclose(np.diag(iou), [1, 1], rtol=1e-5)
        # overlap of the two: inter=1, union=7
        assert iou[0, 1] == pytest.approx(1 / 7, rel=1e-4)


class TestNMS:
    def test_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 10, 10],
                          [1, 1, 11, 11],     # heavy overlap with 0
                          [20, 20, 30, 30]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = np.asarray(V.nms(boxes, scores, iou_threshold=0.5).numpy())
        np.testing.assert_array_equal(keep, [0, 2])

    def test_keeps_all_disjoint(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [9, 9, 10, 10]],
                         np.float32)
        scores = np.array([0.1, 0.9, 0.5], np.float32)
        keep = np.asarray(V.nms(boxes, scores, 0.5).numpy())
        np.testing.assert_array_equal(keep, [1, 2, 0])  # score order

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [9, 9, 10, 10]],
                         np.float32)
        scores = np.array([0.1, 0.9, 0.5], np.float32)
        keep = np.asarray(V.nms(boxes, scores, 0.5, top_k=1).numpy())
        np.testing.assert_array_equal(keep, [1])

    def test_multiclass(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11],
                          [20, 20, 30, 30]], np.float32)
        scores = np.array([[0.9, 0.85, 0.01],    # class 0
                           [0.02, 0.03, 0.8]], np.float32)  # class 1
        out = np.asarray(V.multiclass_nms(boxes, scores,
                                          score_threshold=0.05,
                                          nms_threshold=0.5,
                                          background_label=-1).numpy())
        labels = out[:, 0].astype(int).tolist()
        # class 0: boxes 0/1 overlap → one kept; box 2 below threshold
        assert labels.count(0) == 1 and labels.count(1) == 1

    def test_multiclass_background_default_skips_class0(self):
        """multiclass_nms_op.cc defaults background_label=0."""
        boxes = np.array([[0, 0, 10, 10]], np.float32)
        scores = np.array([[0.99], [0.5]], np.float32)
        out = np.asarray(V.multiclass_nms(boxes, scores,
                                          score_threshold=0.05).numpy())
        assert (out[:, 0] != 0).all() and len(out) == 1
        assert out[0, 1] == pytest.approx(0.5)  # the class-1 detection


class TestRoiOps:
    def test_roi_align_constant_map(self):
        """Constant feature map → every aligned cell equals the constant."""
        x = np.full((1, 3, 16, 16), 2.5, np.float32)
        rois = np.array([[0, 0, 8, 8], [4, 4, 12, 12]], np.float32)
        out = np.asarray(V.roi_align(x, rois, output_size=4).numpy())
        assert out.shape == (2, 3, 4, 4)
        np.testing.assert_allclose(out, 2.5, rtol=1e-5)

    def test_roi_align_gradient_flows(self):
        x = paddle.to_tensor(rs().rand(1, 2, 8, 8).astype("f"))
        x.stop_gradient = False
        rois = np.array([[0, 0, 8, 8]], np.float32)
        out = V.roi_align(x, rois, output_size=2)
        paddle.sum(out).backward()
        g = np.asarray(x.grad.numpy())
        assert g.sum() > 0  # bilinear weights sum to out-cells

    def test_roi_pool_takes_max(self):
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0, 2, 2] = 5.0
        rois = np.array([[0, 0, 4, 4]], np.float32)
        out = np.asarray(V.roi_pool(x, rois, output_size=1).numpy())
        assert out.reshape(()) == pytest.approx(5.0)


class TestYoloBox:
    def test_decode_shapes_and_ranges(self):
        N, A, C, H, W = 2, 3, 4, 5, 5
        x = rs().randn(N, A * (5 + C), H, W).astype("f")
        img = np.array([[160, 160], [320, 320]], np.int32)
        anchors = [10, 13, 16, 30, 33, 23]
        boxes, scores = V.yolo_box(x, img, anchors, C, conf_thresh=0.0)
        b = np.asarray(boxes.numpy())
        s = np.asarray(scores.numpy())
        assert b.shape == (N, A * H * W, 4)
        assert s.shape == (N, A * H * W, C)
        # clip_bbox → inside image
        assert b[0].min() >= 0 and b[0, :, [0, 2]].max() <= 159
        assert (s >= 0).all() and (s <= 1).all()

    def test_conf_thresh_zeroes_scores(self):
        N, A, C, H, W = 1, 1, 2, 2, 2
        x = np.full((N, A * (5 + C), H, W), -10.0, np.float32)  # conf ~0
        img = np.array([[64, 64]], np.int32)
        _, scores = V.yolo_box(x, img, [10, 10], C, conf_thresh=0.5)
        assert np.asarray(scores.numpy()).max() == 0.0


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
        pvar = np.full((2, 4), 0.1, np.float32)
        targets = np.array([[1, 1, 12, 11], [4, 6, 22, 24]], np.float32)
        enc = V.box_coder(priors, pvar, targets, "encode_center_size")
        dec = V.box_coder(priors, pvar, enc, "decode_center_size")
        np.testing.assert_allclose(np.asarray(dec.numpy()), targets,
                                   rtol=1e-4, atol=1e-3)

    def test_decode_3d_per_class(self):
        """[N,M,4] decode (per-class deltas) with axis=0: priors vary
        along dim 0, classes along dim 1."""
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
        pvar = np.ones((2, 4), np.float32)
        deltas = np.zeros((2, 3, 4), np.float32)  # zero deltas → priors
        dec = np.asarray(V.box_coder(priors, pvar, deltas,
                                     "decode_center_size", axis=0).numpy())
        assert dec.shape == (2, 3, 4)
        for m in range(3):
            np.testing.assert_allclose(dec[:, m], priors, rtol=1e-5)


class TestRoiAlignJit:
    def test_roi_align_jits_with_traced_boxes_num(self):
        import jax
        import jax.numpy as jnp

        x = rs().rand(2, 2, 8, 8).astype("f")
        rois = np.array([[0, 0, 8, 8], [0, 0, 4, 4], [2, 2, 6, 6]], "f")

        @jax.jit
        def run(xv, bv, bn):
            return V.roi_align(paddle.Tensor(xv), paddle.Tensor(bv),
                               boxes_num=paddle.Tensor(bn),
                               output_size=2).value

        out = run(jnp.asarray(x), jnp.asarray(rois),
                  jnp.asarray(np.array([1, 2], np.int32)))
        assert out.shape == (3, 2, 2, 2)


class TestPriorBox:
    def test_shapes_and_normalization(self):
        feat = paddle.zeros([1, 8, 4, 4])
        img = paddle.zeros([1, 3, 64, 64])
        boxes, var = V.prior_box(feat, img, min_sizes=[16],
                                 aspect_ratios=[1.0, 2.0], flip=True,
                                 clip=True)
        b = np.asarray(boxes.numpy())
        assert b.shape == (4, 4, 3, 4)  # 1 + (2.0, 0.5) aspect anchors
        assert b.min() >= 0 and b.max() <= 1
        v = np.asarray(var.numpy())
        assert v.shape == b.shape
        np.testing.assert_allclose(v[..., 2], 0.2)
        # square anchor centered in cell 0: size 16/64 = 0.25 normalized
        w = b[0, 0, 0, 2] - b[0, 0, 0, 0]
        assert w == pytest.approx(0.25, abs=1e-5)
