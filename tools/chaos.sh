#!/usr/bin/env bash
# Run the chaos / fault-injection suite (resilience runtime coverage) on
# the CPU backend.  Includes the `slow`-marked multi-process tests that
# tier-1 skips: preemption-resume bitwise equivalence, launcher backoff,
# watchdog abort.  Extra args are passed through to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
    -p no:cacheprovider -p no:randomly "$@"
