#!/usr/bin/env bash
# Durable-checkpoint smoke (the acceptance drill for the checkpoint
# subsystem):
#   1. clean oracle training run → final params
#   2. SIGKILL a trainer MID-SAVE (chaos slow-IO holds the window open
#      between the generation rename and its COMMIT marker) → a real
#      torn generation on disk
#   3. restart: the torn generation is QUARANTINED, restore cascades to
#      the previous generation, and the resumed run ends bitwise equal
#      to the oracle
#   4. elastic rerun: a dp8-saved Model.fit checkpoint resumes on dp1
#   5. the full durability pytest matrix
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi
WORK="$(mktemp -d /tmp/ckpt_smoke.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT

TRAINER="$WORK/trainer.py"
cat > "$TRAINER" <<'PY'
import os, sys
import numpy as np
import jax, jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.resilience import run_resilient

out, ckpt = sys.argv[1], sys.argv[2]
rs = np.random.RandomState(0)
w0 = {"w": jnp.asarray(rs.randn(4, 4) * 0.3, jnp.float32)}
data = [jnp.asarray(rs.randn(8, 4), jnp.float32) for _ in range(8)]
opt = paddle.optimizer.Adam(learning_rate=0.01)

def loss_fn(p, x):
    return jnp.mean((x @ p["w"] - 1.0) ** 2)

@jax.jit
def train(p, s, t, x):
    l, g = jax.value_and_grad(loss_fn)(p, x)
    p2, s2 = opt.apply_pytree(p, g, s, step=t)
    return p2, s2, l

def step_fn(step, st):
    p, s, l = train(st["params"], st["opt"], step, data[step - 1])
    with open(os.path.join(out, "progress"), "w") as f:
        f.write(str(step))
    return {"params": p, "opt": s}, float(l)

with CheckpointManager(ckpt) as mgr:
    state, info = run_resilient(
        step_fn, {"params": w0, "opt": opt.init_pytree(w0)}, mgr,
        num_steps=8, save_interval=2)
np.save(os.path.join(out, "final.npy"), np.asarray(state["params"]["w"]))
PY

echo "== [1/5] clean oracle run"
mkdir -p "$WORK/clean"
python "$TRAINER" "$WORK/clean" "$WORK/clean_ckpt"

echo "== [2/5] SIGKILL mid-save (torn generation)"
mkdir -p "$WORK/torn"
# slow-IO chaos stalls every checkpoint IO step, including the window
# AFTER the generation dir is renamed into place and BEFORE its COMMIT
# marker lands — poll for exactly that state and SIGKILL into it
PADDLE_CHAOS_CKPT_SLOW_IO=1.5 python "$TRAINER" "$WORK/torn" "$WORK/ckpt" &
PID=$!
TORN=""
for _ in $(seq 1 600); do
    for d in "$WORK"/ckpt/[0-9]*; do
        [ -d "$d" ] || continue
        if [ ! -e "$d/COMMIT" ]; then TORN="$d"; break; fi
    done
    # only kill into a LATER generation's window so a prior committed
    # generation exists for the cascade to land on
    if [ -n "$TORN" ] && compgen -G "$WORK/ckpt/[0-9]*/COMMIT" > /dev/null; then
        kill -9 "$PID" 2>/dev/null || true
        break
    fi
    TORN=""
    sleep 0.05
done
wait "$PID" 2>/dev/null || true
if [ -z "$TORN" ]; then
    echo "FAIL: never caught a save between rename and COMMIT"; exit 1
fi
echo "   torn generation left on disk: $TORN"
[ ! -e "$TORN/COMMIT" ] || { echo "FAIL: torn gen has a COMMIT marker"; exit 1; }
[ ! -f "$WORK/torn/final.npy" ] || { echo "FAIL: killed run finished?!"; exit 1; }

echo "== [3/5] restart: quarantine + cascade + bitwise resume"
PADDLE_RESTART_COUNT=1 python "$TRAINER" "$WORK/torn" "$WORK/ckpt" 2> "$WORK/resume.log"
grep -q "REJECTED" "$WORK/resume.log" || { echo "FAIL: no quarantine log"; cat "$WORK/resume.log"; exit 1; }
[ -d "$WORK/ckpt/quarantine" ] || { echo "FAIL: no quarantine dir"; exit 1; }
python - "$WORK" <<'PY'
import sys, numpy as np
w = sys.argv[1]
a = np.load(w + "/clean/final.npy"); b = np.load(w + "/torn/final.npy")
np.testing.assert_array_equal(a, b)
print("   resumed-after-torn final params BITWISE equal to oracle")
PY

echo "== [4/5] elastic rerun: dp8-saved fit checkpoint resumes on dp1"
python - "$WORK" <<'PY'
import sys, numpy as np
import paddle_tpu as paddle
from paddle_tpu.hapi import Model

work = sys.argv[1] + "/elastic"

def model_and_data():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    rs = np.random.RandomState(0)
    x = rs.randn(32, 4).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    ds = paddle.io.TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    m = Model(net)
    m.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters()),
              paddle.nn.CrossEntropyLoss())
    return m, ds

ma, ds = model_and_data()
ma.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
       mesh={"dp": 8}, resume=work)
w8 = {k: np.asarray(p._value) for k, p in ma.network.named_parameters()}

mb, ds = model_and_data()
mb.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
       mesh={"dp": 1}, resume=work)
got = {k: np.asarray(p._value) for k, p in mb.network.named_parameters()}
assert any(not np.array_equal(got[k], w8[k]) for k in w8), \
    "dp1 phase trained nothing after the elastic restore"
print("   dp8-saved checkpoint restored and TRAINED ON on a dp1 mesh")
PY

echo "== [5/5] durability pytest matrix"
python -m pytest tests/test_ckpt_durability.py tests/test_chaos.py -q \
    -p no:cacheprovider -p no:randomly "$@"

echo "ckpt_smoke: ALL PASSED"
