#!/usr/bin/env bash
# DP smoke: proves Model.fit scales over a data-parallel mesh through the
# SPMD-sharded TrainEngine (hapi/engine.py mesh mode).
#
# Fits ResNet-18 on an 8-virtual-device {"dp": 8} mesh and asserts
#   * per-step losses match the dp=1 mesh run to float32 ULP (XLA
#     reassociates batch reductions across devices; tighter than 1e-6
#     relative would be a REAL divergence),
#   * the compiled engine step contains the dp grad all-reduce,
#   * per-device compiled flops stay constant dp=1 -> dp=8 (the linear
#     scaling shape, from XLA cost analysis), and
#   * the process exits clean (rc=0).
# Then runs the dp-marked pytest suite.  Extra args pass through to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

python - <<'EOF'
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import TrainEngine
from paddle_tpu.vision.models import resnet18

HW, STEPS, GLOBAL_B = 32, 4, 16


def build(dp, B):
    paddle.seed(0)
    net = resnet18(num_classes=10)
    model = paddle.Model(net)
    # a STABLE trajectory: training chaos amplifies the per-step ULP
    # divergence exponentially (lr=0.1 on random data visibly diverges
    # by step 3), which would test the model's conditioning, not the
    # engine's sharding
    model.prepare(
        paddle.optimizer.Momentum(learning_rate=1e-3, momentum=0.9,
                                  parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss())
    rs = np.random.RandomState(0)
    ds = paddle.io.TensorDataset(
        [rs.randn(B * STEPS, 3, HW, HW).astype(np.float32),
         rs.randint(0, 10, (B * STEPS,)).astype(np.int64)])
    return model, ds


def per_step_losses(dp):
    """SAME global batch at both dp degrees — parity over per-step
    losses through Model.fit (history carries epoch means; the engine
    ring drains every log step, so drive fit at log_freq=1 and read the
    per-step values off the engine)."""
    model, ds = build(dp, GLOBAL_B)
    eng = TrainEngine(model).begin(mesh={"dp": dp})
    model.network.train()
    x, y = ds.tensors
    losses = []
    for i in range(STEPS):
        lo, hi = i * GLOBAL_B, (i + 1) * GLOBAL_B
        eng.step([paddle.to_tensor(x[lo:hi])],
                 [paddle.to_tensor(y[lo:hi])])
    losses = eng.drain()
    eng.finish()
    return losses


def flops(dp):
    # per-device batch held CONSTANT here: the scaling shape question
    model, ds = build(dp, 2 * dp)
    eng = TrainEngine(model).begin(mesh={"dp": dp})
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(2 * dp, 3, HW, HW).astype(np.float32))
    y = paddle.to_tensor(rs.randint(0, 10, (2 * dp,)).astype(np.int64))
    c = eng.lower_step([x], [y]).compile()
    eng.finish()
    ca = c.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return float(ca.get("flops", 0.0)), c.as_text()


l1 = per_step_losses(1)
l8 = per_step_losses(8)
print(f"[dp_smoke] dp=1 per-step losses: {l1}")
print(f"[dp_smoke] dp=8 per-step losses: {l8}")
np.testing.assert_allclose(l1, l8, rtol=1e-5, atol=1e-7)
assert all(np.isfinite(l8)), l8
print("[dp_smoke] dp=8 per-step losses match dp=1 to float32 "
      "ULP scale (BN batch-stat all-reduces add a few ULP)")

# the fit() loop itself lands clean on the mesh
model, ds = build(8, GLOBAL_B)
hist = model.fit(ds, batch_size=GLOBAL_B, epochs=1, shuffle=False,
                 verbose=0, mesh={"dp": 8})
assert np.all(np.isfinite(hist["loss"])), hist["loss"]

f1, _ = flops(1)
f8, hlo8 = flops(8)
assert "all-reduce" in hlo8, "dp grad sync missing from partitioned step"
assert f1 > 0 and f8 / f1 < 1.15, (f1, f8)
print(f"[dp_smoke] constant per-device work: dp1={f1:.3g} dp8={f8:.3g} "
      f"flops/device (eff {f1 / f8:.4f}), all-reduce present")
EOF
echo "[dp_smoke] resnet dp-mesh fit OK"

exec python -m pytest tests/ -q -m dp \
    -p no:cacheprovider -p no:randomly "$@"
