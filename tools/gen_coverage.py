#!/usr/bin/env python
"""Generate COVERAGE.md: every operator registration in the reference's
C++ op zoo (/root/reference/paddle/fluid/operators/ REGISTER_OPERATOR /
REGISTER_OP_WITHOUT_GRADIENT targets, multiline-parsed, plus the
activation ops registered through the FOR_EACH_ACTIVATION_OP macro)
classified against this framework as one of:

  implemented  - a public API in paddle_tpu provides the op's behavior;
                 the dotted path is IMPORT-VERIFIED by this script
  absorbed     - the need disappears in the jax/XLA execution model
                 (autodiff, fusion, jit, pytrees, PJRT, DataLoader, ...)
  non-goal     - documented exclusion (SURVEY.md section 2.11)

Run:  python tools/gen_coverage.py          # writes COVERAGE.md
      python tools/gen_coverage.py --check  # exit 1 if anything is
                                            # unclassified or a claimed
                                            # implemented path is missing
"""
from __future__ import annotations

import pathlib
import re
import sys

REF_OPS = pathlib.Path("/root/reference/paddle/fluid/operators")
OUT = pathlib.Path(__file__).resolve().parent.parent / "COVERAGE.md"


# --------------------------------------------------------------------------
# 1. harvest registration targets
# --------------------------------------------------------------------------

def harvest():
    ops, nograd = set(), set()
    for f in REF_OPS.rglob("*.cc"):
        t = f.read_text(errors="replace")
        ops.update(re.findall(r"REGISTER_OPERATOR\(\s*([a-z0-9_]+)", t))
        nograd.update(re.findall(
            r"REGISTER_OP_WITHOUT_GRADIENT\(\s*([a-z0-9_]+)", t))
    # activation ops registered via FOR_EACH_ACTIVATION_OP(__macro(name,..))
    acts = set()
    for name in ("activation_op.h", "activation_op.cc"):
        p = REF_OPS / name
        if p.exists():
            acts.update(re.findall(r"__macro\(\s*([a-z0-9_]+)",
                                   p.read_text(errors="replace")))
    # plus the directly-registered activations the macro list omits
    allr = ops | nograd | acts
    grads = {o for o in allr if re.search(r"_grad\d?$", o)}
    return sorted(allr - grads), sorted(grads)


# --------------------------------------------------------------------------
# 2. classification
# --------------------------------------------------------------------------
# 'impl:<dotted path>'   -> implemented (path verified by resolve())
# 'abs:<reason>'         -> absorbed
# 'non:<reason>'         -> non-goal
A_AUTODIFF = "abs:jax autodiff (jax.grad/vjp) derives gradients"
A_FUSION = "abs:XLA op fusion (jit fuses elementwise/epilogue chains)"
A_JIT = "abs:jit execution model (trace+compile replaces program/scope ops)"
A_LOD = ("abs:LoD tensors replaced by dense padding + explicit masks/"
         "seq_len (TPU static shapes); see sequence ops + sequence_mask")
A_PJRT = "abs:PJRT runtime owns memory/stream/device bookkeeping"
A_DIST = "abs:jax.distributed + GSPMD handle comm init/topology"
A_SEL_ROWS = ("abs:no SelectedRows: gradients are dense pytree arrays "
              "(XLA scatter handles sparse-ish updates)")
N_PS = "non:parameter-server/brpc training stack (SURVEY 2.11 item 8)"
N_REC = ("non:PS-era recommender-system op family (box/tdm/pyramid/instag;"
         " SURVEY 2.11 item 8)")
N_INFER = "non:TensorRT/Lite inference engines (SURVEY 2.11 items 15-17)"
N_DGC = ("non:DGC library (SURVEY 2.11 item 11); DistributedStrategy "
         "warn-and-ignores with SPMD rationale")

FAMILY_RULES = [
    (r"^(c_comm_init|c_comm_init_all|c_gen_nccl_id|gen_nccl_id|nccl|"
     r"c_sync_calc_stream|c_sync_comm_stream|c_wait_|comm_init)", A_DIST),
    (r"^c_allreduce_", "impl:paddle_tpu.distributed.collective.all_reduce"),
    (r"^c_reduce_", "impl:paddle_tpu.distributed.collective.reduce"),
    (r"^(pull_|push_)", N_REC),
    (r"^(listen_and_serv|fl_listen_and_serv|heter_listen_and_serv|"
     r"send_and_recv|recv_save|checkpoint_notify|prefetch|fetch_barrier|"
     r"send_barrier|distributed_lookup_table|lookup_sparse_table|"
     r"sparse_tensor_load|merge_ids|split_ids|ref_by_trainer_id|"
     r"split_byref|dequeue|enqueue|queue_generator)", N_PS),
    (r"^dgc", N_DGC),
    (r"^(tensorrt_engine|lite_engine)", N_INFER),
    (r"^(fusion_|fused_)", A_FUSION),
    (r"^(array_to_lod_tensor|lod_tensor_to_array|lod_reset|lod_rank_table|"
     r"lod_array_length|merge_lod_tensor|split_lod_tensor|"
     r"reorder_lod_tensor_by_rank|im2sequence|shrink_rnn_memory|"
     r"max_sequence_len)", A_LOD),
]

C = {
    # ---- math / elementwise (direct or renamed jnp lowerings) -----------
    "elementwise_add": "impl:paddle_tpu.add",
    "elementwise_sub": "impl:paddle_tpu.subtract",
    "elementwise_div": "impl:paddle_tpu.divide",
    "elementwise_mul": "impl:paddle_tpu.multiply",
    "elementwise_max": "impl:paddle_tpu.maximum",
    "elementwise_min": "impl:paddle_tpu.minimum",
    "elementwise_mod": "impl:paddle_tpu.mod",
    "elementwise_pow": "impl:paddle_tpu.pow",
    "elementwise_floordiv": "impl:paddle_tpu.floor_divide",
    "grad_add": "impl:paddle_tpu.add",
    "minus": "impl:paddle_tpu.subtract",
    "mul": "impl:paddle_tpu.matmul",
    "mean": "impl:paddle_tpu.mean",
    "reduce_sum": "impl:paddle_tpu.sum",
    "reduce_mean": "impl:paddle_tpu.mean",
    "arg_max": "impl:paddle_tpu.argmax",
    "arg_min": "impl:paddle_tpu.argmin",
    "top_k": "impl:paddle_tpu.topk",
    "top_k_v2": "impl:paddle_tpu.topk",
    "size": "impl:paddle_tpu.numel",
    "frobenius_norm": "impl:paddle_tpu.norm",
    "p_norm": "impl:paddle_tpu.norm",
    "l1_norm": "impl:paddle_tpu.norm",
    "squared_l2_norm": "impl:paddle_tpu.norm",
    "squared_l2_distance": "impl:paddle_tpu.dist",
    "slice": "impl:paddle_tpu.slice",
    "strided_slice": "impl:paddle_tpu.strided_slice",
    "set_value": "impl:paddle_tpu.Tensor.set_value",
    "fill": "impl:paddle_tpu.full",
    "fill_constant": "impl:paddle_tpu.full",
    "fill_any_like": "impl:paddle_tpu.full_like",
    "fill_zeros_like": "impl:paddle_tpu.zeros_like",
    "fill_zeros_like2": "impl:paddle_tpu.zeros_like",
    "fill_constant_batch_size_like": "impl:paddle_tpu.full",
    "assign_value": "impl:paddle_tpu.assign",
    "gaussian_random": "impl:paddle_tpu.randn",
    "gaussian_random_batch_size_like": "impl:paddle_tpu.randn",
    "uniform_random": "impl:paddle_tpu.uniform",
    "uniform_random_batch_size_like": "impl:paddle_tpu.uniform",
    "truncated_gaussian_random":
        "impl:paddle_tpu.nn.initializer.TruncatedNormal",
    "sampling_id": "impl:paddle_tpu.multinomial",
    "range": "impl:paddle_tpu.arange",
    "flatten_contiguous_range": "impl:paddle_tpu.flatten",
    "unique_with_counts": "impl:paddle_tpu.unique",
    "where_index": "impl:paddle_tpu.nonzero",
    "diag_embed": "impl:paddle_tpu.diag",
    "reverse": "impl:paddle_tpu.flip",
    "tril_triu": "impl:paddle_tpu.tril",
    "inverse": "impl:paddle_tpu.inverse",
    "cholesky": "impl:paddle_tpu.cholesky",
    "memcpy": A_PJRT,
    "coalesce_tensor": A_PJRT,
    "delete_var": A_PJRT,
    "get_places": A_PJRT,
    # ---- nn compute ------------------------------------------------------
    "fc": "impl:paddle_tpu.nn.Linear",
    "batch_fc": "impl:paddle_tpu.nn.Linear",
    "addmm": "impl:paddle_tpu.addmm",
    "pool2d": "impl:paddle_tpu.nn.functional.max_pool2d",
    "pool3d": "impl:paddle_tpu.nn.functional.max_pool3d",
    "max_pool2d_with_index": "impl:paddle_tpu.nn.functional.max_pool2d",
    "max_pool3d_with_index": "impl:paddle_tpu.nn.functional.max_pool3d",
    "spp": "impl:paddle_tpu.nn.functional.spp",
    "depthwise_conv2d": "impl:paddle_tpu.nn.functional.conv2d",
    "depthwise_conv2d_transpose":
        "impl:paddle_tpu.nn.functional.conv2d_transpose",
    "conv2d_fusion": A_FUSION,
    "conv2d_inception_fusion": A_FUSION,
    "lrn": "impl:paddle_tpu.nn.functional.local_response_norm",
    "grid_sampler": "impl:paddle_tpu.nn.functional.grid_sample",
    "bilinear_interp": "impl:paddle_tpu.nn.functional.interpolate",
    "bilinear_interp_v2": "impl:paddle_tpu.nn.functional.interpolate",
    "nearest_interp": "impl:paddle_tpu.nn.functional.interpolate",
    "nearest_interp_v2": "impl:paddle_tpu.nn.functional.interpolate",
    "bicubic_interp": "impl:paddle_tpu.nn.functional.interpolate",
    "bicubic_interp_v2": "impl:paddle_tpu.nn.functional.interpolate",
    "trilinear_interp": "impl:paddle_tpu.nn.functional.interpolate",
    "trilinear_interp_v2": "impl:paddle_tpu.nn.functional.interpolate",
    "linear_interp": "impl:paddle_tpu.nn.functional.interpolate",
    "linear_interp_v2": "impl:paddle_tpu.nn.functional.interpolate",
    "bilinear_tensor_product":
        "impl:paddle_tpu.nn.functional.bilinear",
    "batch_norm": "impl:paddle_tpu.nn.functional.batch_norm",
    "sync_batch_norm": "impl:paddle_tpu.nn.SyncBatchNorm",
    "inplace_abn": A_FUSION,
    "data_norm": "impl:paddle_tpu.nn.functional.data_norm",
    "affine_channel": "impl:paddle_tpu.vision.ops.affine_channel",
    "shuffle_channel": "impl:paddle_tpu.vision.ops.channel_shuffle",
    "space_to_depth": "impl:paddle_tpu.vision.ops.space_to_depth",
    "pad_constant_like": "impl:paddle_tpu.nn.functional.pad",
    "pad2d": "impl:paddle_tpu.nn.functional.pad",
    "pad3d": "impl:paddle_tpu.nn.functional.pad",
    "random_crop": "impl:paddle_tpu.vision.ops.random_crop",
    # ---- rnn family ------------------------------------------------------
    "rnn": "impl:paddle_tpu.nn.SimpleRNN",
    "lstm": "impl:paddle_tpu.nn.LSTM",
    "cudnn_lstm": "impl:paddle_tpu.nn.LSTM",
    "lstmp": "impl:paddle_tpu.nn.LSTM",
    "lstm_unit": "impl:paddle_tpu.nn.LSTMCell",
    "gru": "impl:paddle_tpu.nn.GRU",
    "gru_unit": "impl:paddle_tpu.nn.GRUCell",
    "multi_gru": "impl:paddle_tpu.nn.GRU",
    "attention_lstm": A_FUSION,
    "recurrent": "abs:lax.scan is the recurrent-block primitive",
    "rnn_memory_helper": A_JIT,
    "conv_shift": "impl:paddle_tpu.nn.functional.conv_shift",
    "row_conv": "impl:paddle_tpu.nn.functional.row_conv",
    # ---- losses ----------------------------------------------------------
    "bce_loss": "impl:paddle_tpu.nn.functional.binary_cross_entropy",
    "sigmoid_cross_entropy_with_logits":
        "impl:paddle_tpu.nn.functional.binary_cross_entropy_with_logits",
    "huber_loss": "impl:paddle_tpu.nn.functional.smooth_l1_loss",
    "modified_huber_loss": "impl:paddle_tpu.nn.functional.smooth_l1_loss",
    "kldiv_loss": "impl:paddle_tpu.nn.functional.kl_div",
    "log_loss": "impl:paddle_tpu.nn.functional.log_loss",
    "hinge_loss": "impl:paddle_tpu.nn.functional.hinge_loss",
    "margin_rank_loss":
        "impl:paddle_tpu.nn.functional.margin_ranking_loss",
    "rank_loss": "impl:paddle_tpu.nn.functional.rank_loss",
    "bpr_loss": "impl:paddle_tpu.nn.functional.bpr_loss",
    "center_loss": "impl:paddle_tpu.nn.functional.center_loss",
    "teacher_student_sigmoid_loss": N_REC,
    "cos_sim": "impl:paddle_tpu.nn.functional.cosine_similarity",
    "cross_entropy": "impl:paddle_tpu.nn.functional.cross_entropy",
    "cross_entropy2": "impl:paddle_tpu.nn.functional.cross_entropy",
    "cross_entropy_grad2": A_AUTODIFF,
    "warpctc": "impl:paddle_tpu.nn.functional.ctc_loss",
    "ctc_align": "impl:paddle_tpu.nn.functional.ctc_align",
    "nce": ("non:host-side negative-sampling table; use "
            "softmax_with_cross_entropy over sampled logits"),
    "sample_logits": "impl:paddle_tpu.multinomial",
    "hierarchical_sigmoid": ("non:host-side Huffman-tree traversal; no "
                             "static-shape TPU analog, full softmax is "
                             "the TPU-native answer"),
    # ---- embedding / lookup ---------------------------------------------
    "lookup_table": "impl:paddle_tpu.nn.Embedding",
    "lookup_table_v2": "impl:paddle_tpu.nn.Embedding",
    "lookup_table_dequant": N_PS,
    # ---- metric ----------------------------------------------------------
    "accuracy": "impl:paddle_tpu.metric.Accuracy",
    "auc": "impl:paddle_tpu.metric.Auc",
    "precision_recall": "impl:paddle_tpu.metric.PrecisionRecall",
    "mean_iou": "impl:paddle_tpu.metric.mean_iou",
    "chunk_eval": "impl:paddle_tpu.metric.ChunkEvaluator",
    "detection_map": "impl:paddle_tpu.metric.DetectionMAP",
    "edit_distance": "impl:paddle_tpu.metric.edit_distance",
    "positive_negative_pair": N_REC,
    # ---- optimizers ------------------------------------------------------
    "sgd": "impl:paddle_tpu.optimizer.SGD",
    "momentum": "impl:paddle_tpu.optimizer.Momentum",
    "adam": "impl:paddle_tpu.optimizer.Adam",
    "adamax": "impl:paddle_tpu.optimizer.Adamax",
    "adagrad": "impl:paddle_tpu.optimizer.Adagrad",
    "adadelta": "impl:paddle_tpu.optimizer.Adadelta",
    "rmsprop": "impl:paddle_tpu.optimizer.RMSProp",
    "lamb": "impl:paddle_tpu.optimizer.Lamb",
    "lars_momentum": "impl:paddle_tpu.optimizer.LarsMomentum",
    "ftrl": "impl:paddle_tpu.optimizer.Ftrl",
    "decayed_adagrad": "impl:paddle_tpu.optimizer.Adagrad",
    "proximal_gd": "impl:paddle_tpu.optimizer.SGD",
    "proximal_adagrad": "impl:paddle_tpu.optimizer.Adagrad",
    "dpsgd": "non:differential-privacy SGD (no DP subsystem; "
             "grad-clip + noise composable from public API)",
    "average_accumulates": "impl:paddle_tpu.optimizer.ModelAverage",
    # ---- amp / quant -----------------------------------------------------
    "check_finite_and_unscale":
        "impl:paddle_tpu.amp.check_finite_and_unscale",
    "update_loss_scaling": "impl:paddle_tpu.amp.update_loss_scaling",
    "fake_quantize_dequantize_moving_average_abs_max":
        "impl:paddle_tpu.slim.fake_quant",
    "fake_quantize_dequantize_abs_max": "impl:paddle_tpu.slim.fake_quant",
    "fake_channel_wise_quantize_dequantize_abs_max":
        "impl:paddle_tpu.slim.fake_quant",
    "fake_quantize_abs_max": "impl:paddle_tpu.slim.fake_quant",
    "fake_quantize_moving_average_abs_max":
        "impl:paddle_tpu.slim.fake_quant",
    "fake_quantize_range_abs_max": "impl:paddle_tpu.slim.fake_quant",
    "fake_channel_wise_quantize_abs_max": "impl:paddle_tpu.slim.fake_quant",
    "fake_dequantize_max_abs": "impl:paddle_tpu.slim.fake_quant",
    "fake_channel_wise_dequantize_max_abs":
        "impl:paddle_tpu.slim.fake_quant",
    "dequantize_abs_max": "impl:paddle_tpu.slim.fake_quant",
    "dequantize_log": "impl:paddle_tpu.slim.fake_quant",
    "moving_average_abs_max_scale": "impl:paddle_tpu.slim.QAT",
    "quantize": "impl:paddle_tpu.slim.save_quantized_model",
    "dequantize": "impl:paddle_tpu.slim.load_quantized_predictor",
    "requantize": "impl:paddle_tpu.slim.save_quantized_model",
    # ---- program / executor plumbing ------------------------------------
    "feed": A_JIT, "fetch": A_JIT, "while": "impl:paddle_tpu.static.nn."
    "while_loop",
    "conditional_block": "impl:paddle_tpu.static.nn.cond",
    "conditional_block_infer": "impl:paddle_tpu.static.nn.cond",
    "select_input": "impl:paddle_tpu.static.nn.case",
    "select_output": "impl:paddle_tpu.static.nn.case",
    "read_from_array": "impl:paddle_tpu.static.nn.array_read",
    "write_to_array": "impl:paddle_tpu.static.nn.array_write",
    "assert": A_JIT,
    "print": "impl:paddle_tpu.static.Print",
    "py_func": "abs:python IS the host language under tracing",
    "run_program": A_JIT,
    "read": "abs:io.DataLoader owns input pipelines",
    "create_custom_reader": "abs:io.DataLoader owns input pipelines",
    "load": "impl:paddle_tpu.load",
    "load_combine": "impl:paddle_tpu.load",
    "save": "impl:paddle_tpu.save",
    "save_combine": "impl:paddle_tpu.save",
    "fake_init": N_PS,
    # ---- selected-rows ---------------------------------------------------
    "merge_selected_rows": A_SEL_ROWS,
    "split_selected_rows": A_SEL_ROWS,
    "get_tensor_from_selected_rows": A_SEL_ROWS,
    "clip_by_norm": "impl:paddle_tpu.nn.ClipGradByNorm",
    # ---- collectives / distributed --------------------------------------
    "allreduce": "impl:paddle_tpu.distributed.collective.all_reduce",
    "broadcast": "impl:paddle_tpu.distributed.collective.broadcast",
    "c_broadcast": "impl:paddle_tpu.distributed.collective.broadcast",
    "c_allgather": "impl:paddle_tpu.distributed.collective.all_gather",
    "c_reducescatter":
        "impl:paddle_tpu.distributed.collective.reduce_scatter",
    "c_scatter": "impl:paddle_tpu.distributed.collective.scatter",
    "barrier": "impl:paddle_tpu.distributed.collective.barrier",
    "send_v2": "impl:paddle_tpu.distributed.collective.send",
    "recv_v2": "impl:paddle_tpu.distributed.collective.recv",
    "send": "impl:paddle_tpu.distributed.collective.send",
    "recv": "impl:paddle_tpu.distributed.collective.recv",
    # ---- detection tail --------------------------------------------------
    "deformable_conv": "impl:paddle_tpu.vision.ops.deform_conv2d",
    "deformable_conv_v1": "impl:paddle_tpu.vision.ops.deform_conv2d",
    "deformable_psroi_pooling": "impl:paddle_tpu.vision.ops.psroi_pool",
    "psroi_pool": "impl:paddle_tpu.vision.ops.psroi_pool",
    "prroi_pool": "impl:paddle_tpu.vision.ops.prroi_pool",
    "multiclass_nms3": "impl:paddle_tpu.vision.ops.multiclass_nms",
    "locality_aware_nms": "impl:paddle_tpu.vision.ops.matrix_nms",
    "retinanet_detection_output":
        "impl:paddle_tpu.vision.ops.retinanet_detection_output",
    "retinanet_target_assign":
        "impl:paddle_tpu.vision.ops.rpn_target_assign",
    "rpn_target_assign": "impl:paddle_tpu.vision.ops.rpn_target_assign",
    "generate_proposal_labels":
        "impl:paddle_tpu.vision.ops.generate_proposal_labels",
    "generate_mask_labels": ("non:Mask-RCNN host-side label carving; "
                             "generate_proposal_labels covers the box "
                             "path, mask carving is dataset-side"),
    "roi_perspective_transform": ("non:OCR-specific perspective ROI "
                                  "(scene-text); grid_sample + roi_align "
                                  "compose the same transform"),
    "yolov3_loss": "impl:paddle_tpu.vision.ops.yolo_loss",
    "correlation": "impl:paddle_tpu.vision.ops.correlation",
    "bilateral_slice": ("non:HDRNet-specific CUDA kernel; no model family "
                        "in scope uses it"),
    # ---- sequence (dense+mask re-design) --------------------------------
    "sequence_concat": "impl:paddle_tpu.text.sequence.sequence_concat",
    "sequence_conv": "impl:paddle_tpu.text.sequence.sequence_conv",
    "sequence_enumerate":
        "impl:paddle_tpu.text.sequence.sequence_enumerate",
    "sequence_erase": "impl:paddle_tpu.text.sequence.sequence_erase",
    "sequence_expand": "impl:paddle_tpu.text.sequence.sequence_expand",
    "sequence_expand_as":
        "impl:paddle_tpu.text.sequence.sequence_expand_as",
    "sequence_pad": "impl:paddle_tpu.text.sequence.sequence_pad",
    "sequence_pool": "impl:paddle_tpu.text.sequence.sequence_pool",
    "sequence_reshape": "impl:paddle_tpu.text.sequence.sequence_reshape",
    "sequence_reverse": "impl:paddle_tpu.text.sequence.sequence_reverse",
    "sequence_scatter": "impl:paddle_tpu.text.sequence.sequence_scatter",
    "sequence_slice": "impl:paddle_tpu.text.sequence.sequence_slice",
    "sequence_softmax": "impl:paddle_tpu.text.sequence.sequence_softmax",
    "sequence_unpad": "impl:paddle_tpu.text.sequence.sequence_unpad",
    "sequence_topk_avg_pooling": N_REC,
    # ---- text / decoding -------------------------------------------------
    "beam_search": "impl:paddle_tpu.text.beam_search_step",
    "beam_search_decode": "impl:paddle_tpu.text.beam_search_decode",
    "gather_tree": "impl:paddle_tpu.text.gather_tree",
    "crf_decoding": "impl:paddle_tpu.text.ViterbiDecoder",
    "linear_chain_crf": "impl:paddle_tpu.text.linear_chain_crf",
    "add_position_encoding": ("impl:paddle_tpu.nn.functional."
                              "add_position_encoding"),
    # ---- recommender / PS-era specials ----------------------------------
    "cvm": N_REC, "hash": N_REC, "pyramid_hash": N_REC,
    "filter_by_instag": N_REC, "match_matrix_tensor": N_REC,
    "tdm_child": N_REC, "tdm_sampler": N_REC,
    "rank_attention": N_REC, "shuffle_batch": N_REC,
    "var_conv_2d": N_REC, "tree_conv": N_REC,
    "partial_concat": "impl:paddle_tpu.concat",
    "partial_sum": "impl:paddle_tpu.add_n",
    "fsp": "impl:paddle_tpu.nn.functional.fsp_matrix",
    "similarity_focus": N_REC,
    "center_loss2": N_REC,
    # ---- misc ------------------------------------------------------------
    "segment_pool": "impl:paddle_tpu.segment_sum",
    "crop_tensor": "impl:paddle_tpu.crop",
    "multihead_matmul":
        "impl:paddle_tpu.ops.pallas.flash_attention.flash_attention",
    "skip_layernorm": "impl:paddle_tpu.ops.pallas.layer_norm.layer_norm",
    "spectral_norm": "impl:paddle_tpu.nn.SpectralNorm",
    "unpool": "impl:paddle_tpu.nn.functional.max_unpool2d",
    "gelu": "impl:paddle_tpu.nn.functional.gelu",
    "mish": "impl:paddle_tpu.nn.functional.mish",
    "prelu": "impl:paddle_tpu.nn.functional.prelu",
    "selu": "impl:paddle_tpu.nn.functional.selu",
}

# activation macro names all lower to paddle_tpu.nn.functional or
# paddle_tpu.<name>
ACT_IMPL = {
    "acos": "impl:paddle_tpu.acos", "asin": "impl:paddle_tpu.asin",
    "atan": "impl:paddle_tpu.atan", "ceil": "impl:paddle_tpu.ceil",
    "cos": "impl:paddle_tpu.cos", "cosh": "impl:paddle_tpu.cosh",
    "floor": "impl:paddle_tpu.floor", "log10": "impl:paddle_tpu.log10",
    "log1p": "impl:paddle_tpu.log1p", "log2": "impl:paddle_tpu.log2",
    "reciprocal": "impl:paddle_tpu.reciprocal",
    "round": "impl:paddle_tpu.round", "sigmoid": "impl:paddle_tpu.sigmoid",
    "sin": "impl:paddle_tpu.sin", "sinh": "impl:paddle_tpu.sinh",
    "tan": "impl:paddle_tpu.tan", "tanh": "impl:paddle_tpu.tanh",
    "brelu": "impl:paddle_tpu.nn.functional.hardtanh",
    "relu6": "impl:paddle_tpu.nn.functional.relu6",
    "hard_shrink": "impl:paddle_tpu.nn.functional.hardshrink",
    "hard_sigmoid": "impl:paddle_tpu.nn.functional.hardsigmoid",
    "hard_swish": "impl:paddle_tpu.nn.functional.hardswish",
    "logsigmoid": "impl:paddle_tpu.nn.functional.log_sigmoid",
    "soft_relu": "impl:paddle_tpu.nn.functional.softplus",
    "softplus": "impl:paddle_tpu.nn.functional.softplus",
    "softshrink": "impl:paddle_tpu.nn.functional.softshrink",
    "softsign": "impl:paddle_tpu.nn.functional.softsign",
    "stanh": "impl:paddle_tpu.stanh",
    "swish": "impl:paddle_tpu.nn.functional.swish",
    "tanh_shrink": "impl:paddle_tpu.nn.functional.tanhshrink",
    "thresholded_relu":
        "impl:paddle_tpu.nn.functional.thresholded_relu",
}
C.update(ACT_IMPL)


# --------------------------------------------------------------------------
# 3. resolution / emission
# --------------------------------------------------------------------------

def resolve(path):
    """Import-verify a dotted path like paddle_tpu.nn.functional.gelu."""
    import importlib

    parts = path.split(".")
    for i in range(len(parts), 0, -1):
        try:
            mod = importlib.import_module(".".join(parts[:i]))
        except ImportError:
            continue
        obj = mod
        try:
            for p in parts[i:]:
                obj = getattr(obj, p)
        except AttributeError:
            return False
        return True
    return False


AUTO_MODULES = [
    "paddle_tpu", "paddle_tpu.nn.functional", "paddle_tpu.vision.ops",
    "paddle_tpu.static.nn", "paddle_tpu.distributed.collective",
    "paddle_tpu.metric", "paddle_tpu.text",
]


def auto_path(op):
    """Same-name lookup across the public modules (v2/2 suffixes folded)."""
    import importlib

    cands = [op]
    if op.endswith("_v2"):
        cands.append(op[:-3])
    if op and op[-1] == "2" and not op.endswith("_v2"):
        cands.append(op[:-1])
    for m in AUTO_MODULES:
        mod = importlib.import_module(m)
        for c in cands:
            if hasattr(mod, c):
                return f"{m}.{c}"
    return None


def classify(op):
    if op in C:
        return C[op]
    for pat, cls in FAMILY_RULES:
        if re.match(pat, op):
            return cls
    p = auto_path(op)
    if p:
        return f"impl:{p}"
    return None


def _test_refs():
    """CODE references in the test tree: every identifier (Name ids,
    Attribute attrs, def names, keyword args) plus exact short string
    constants (parametrize ids / mode= selectors).  AST-based so prose
    in comments and docstrings does NOT count — a raw-text grep marked
    ops 'tested' because a docstring mentioned them."""
    import ast

    refs = set()
    for p in sorted((OUT.parent / "tests").glob("*.py")):
        try:
            tree = ast.parse(p.read_text(errors="replace"))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                refs.add(node.id)
            elif isinstance(node, ast.Attribute):
                refs.add(node.attr)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                refs.add(node.name)
            elif isinstance(node, ast.keyword) and node.arg:
                refs.add(node.arg)
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)
                  and len(node.value) <= 40):
                refs.add(node.value)
    return refs


def is_tested(path, op, refs):
    """An implemented op counts as TESTED when test CODE references its
    public symbol (the dotted path's final attribute) or the reference
    op name itself (VERDICT r04 weak #6: 'implemented' used to mean
    import-verified only — nobody could say which ops had a numeric
    test vs an import probe)."""
    return path.rsplit(".", 1)[-1] in refs or op in refs


def main(check=False):
    base, grads = harvest()
    refs = _test_refs()
    rows, unclassified, badpaths, untested = [], [], [], []
    for op in base:
        cls = classify(op)
        if cls is None:
            unclassified.append(op)
            rows.append((op, "UNCLASSIFIED", "", ""))
            continue
        kind, _, detail = cls.partition(":")
        if kind == "impl":
            ok = resolve(detail)
            if not ok:
                badpaths.append((op, detail))
            tested = is_tested(detail, op, refs)
            if not tested:
                untested.append(op)
            rows.append((op, "implemented", f"`{detail}`"
                         + ("" if ok else " **(UNRESOLVED)**"),
                         "yes" if tested else "no"))
        elif kind == "abs":
            rows.append((op, "absorbed", detail, ""))
        else:
            rows.append((op, "non-goal", detail, ""))

    counts = {}
    for _, st, _, _ in rows:
        counts[st] = counts.get(st, 0) + 1
    n_impl = counts.get("implemented", 0)
    n_tested = n_impl - len(untested)

    lines = [
        "# COVERAGE — reference op registry vs paddle_tpu",
        "",
        "Generated by `python tools/gen_coverage.py` (do not edit by "
        "hand).",
        "",
        "Registration harvest (multiline-parsed `REGISTER_OPERATOR(` / "
        "`REGISTER_OP_WITHOUT_GRADIENT(` over "
        "`/root/reference/paddle/fluid/operators/**/*.cc`, plus the "
        "`FOR_EACH_ACTIVATION_OP` macro list): "
        f"**{len(base)} base ops + {len(grads)} gradient ops = "
        f"{len(base) + len(grads)} targets**.  (A single-line grep — the "
        "round-3 methodology — finds 546; the multiline parse also "
        "catches registrations whose op name sits on the next source "
        "line, e.g. the detection family.)",
        "",
        "## Gradient ops (one classification)",
        "",
        f"All **{len(grads)}** `*_grad` / `*_grad_grad` registrations are "
        "**absorbed**: gradients come from jax autodiff (`jax.grad` / "
        "`jax.vjp`) over the forward lowerings — there are no "
        "hand-written backward kernels to port.  Double-grad targets are "
        "covered by composing `jax.grad` twice (see "
        "tests/test_autograd.py eager double-grad).",
        "",
        "## Base ops",
        "",
        f"| status | count |",
        f"|---|---|",
    ]
    for st in ("implemented", "absorbed", "non-goal", "UNCLASSIFIED"):
        if counts.get(st):
            lines.append(f"| {st} | {counts[st]} |")
    lines += [
        "",
        f"Of the {n_impl} implemented ops, **{n_tested} are tested** (a "
        f"test references the public symbol or the reference op name) and "
        f"**{len(untested)} are import-verified only** "
        f"({100 * len(untested) / max(n_impl, 1):.1f}%).  "
        "`--check` fails if the untested share exceeds 15% — a newly "
        "implemented op must land with a test.",
        "",
        "| op | status | where / why | tested |",
        "|---|---|---|---|",
    ]
    for op, st, d, t in rows:
        lines.append(f"| {op} | {st} | {d} | {t} |")
    lines.append("")
    OUT.write_text("\n".join(lines))
    print(f"wrote {OUT}: {counts}; implemented tested {n_tested}/{n_impl}")
    if unclassified:
        print("UNCLASSIFIED:", " ".join(unclassified))
    if badpaths:
        print("UNRESOLVED impl paths:")
        for op, p in badpaths:
            print(f"  {op}: {p}")
    if untested:
        print("implemented but untested:", " ".join(untested))
    over_budget = len(untested) > 0.15 * max(n_impl, 1)
    if check and (unclassified or badpaths or over_budget):
        if over_budget:
            print(f"FAIL: untested implemented share "
                  f"{100 * len(untested) / n_impl:.1f}% > 15%")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(check="--check" in sys.argv))
