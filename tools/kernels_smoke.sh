#!/usr/bin/env bash
# Kernels smoke: proves the Pallas hot path (masked flash attention,
# paged decode attention, softmax-xent, bias-gelu) in CPU interpret
# mode end to end:
#
#   1. bench.py --config kernels — per-kernel fwd/bwd parity vs XLA
#      (references cast to the kernel compute dtype, per-kernel
#      tolerances) plus a flag-on/off masked training step through the
#      ops/fused dispatch with per-op attribution.
#   2. bench.py --config genserve — the continuous-batching engine,
#      whose decode_tokens_per_sec now sits in the perf baseline.
#   3. tools/perf_gate.py over both runs (PADDLE_SKIP_PERF_GATE=1 skips).
#   4. the kernels-marked pytest suite (parity, sharding, remat,
#      dispatch, fallback-counter pins).  Extra args pass to pytest.
#
# On a TPU host the same bench config validates against Mosaic instead
# of interpret mode; this smoke is the CPU tier.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
OUT_DIR="$(mktemp -d /tmp/paddle_kernels_out.XXXXXX)"
trap 'rm -rf "$OUT_DIR"' EXIT

for cfg in kernels genserve; do
    out="$OUT_DIR/bench_$cfg.out"
    echo "[kernels_smoke] bench --config $cfg"
    python bench.py --config "$cfg" > "$out" \
        || { echo "[kernels_smoke] bench $cfg FAILED"; exit 1; }
    tail -n 1 "$out"
done

# the kernels config reports value=1.0 only when every kernel is inside
# its tolerance AND the flag-on step recorded zero Pallas fallbacks
python - "$OUT_DIR/bench_kernels.out" <<'EOF'
import json, sys
last = None
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{") and '"metric"' in line:
        last = json.loads(line)
if last is None:
    sys.exit("no result line in kernels bench output")
if last["value"] != 1.0:
    sys.exit(f"kernel parity failed: {json.dumps(last['kernel_max_errs'])} "
             f"fallbacks={last['pallas_fallbacks_during_flag_on']}")
print("[kernels_smoke] parity OK:", json.dumps(last["kernel_max_errs"]))
EOF

if [ "${PADDLE_SKIP_PERF_GATE:-0}" != "1" ]; then
    python tools/perf_gate.py --subset \
        --run "$OUT_DIR/bench_kernels.out" \
        --run "$OUT_DIR/bench_genserve.out" \
        || { echo "[kernels_smoke] perf gate FAILED"; exit 1; }
fi

exec python -m pytest tests/ -q -m kernels \
    -p no:cacheprovider -p no:randomly "$@"
