#!/usr/bin/env bash
# Framework-aware static analysis gate (paddle_tpu.analysis, PTA001-006).
#
# Exits nonzero on any NEW finding (not in tools/analysis_baseline.json)
# or any STALE baseline entry (grandfathered code that no longer exists —
# the baseline must shrink with the tree).  Run with --write-baseline to
# refresh the baseline after intentionally grandfathering something; add
# the justification to the new entry before committing.
#
# Usage:
#   tools/lint.sh                # gate the live tree (CI / preflight)
#   tools/lint.sh --format json  # machine-readable report
#   tools/lint.sh --select PTA003,PTA004
#   tools/lint.sh --write-baseline
set -uo pipefail
cd "$(dirname "$0")/.."

BASELINE="tools/analysis_baseline.json"
# the linter is pure-AST but lives inside the package: keep jax quiet/CPU
# in case the package import pulls it in
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m paddle_tpu.analysis paddle_tpu \
    --root . --baseline "$BASELINE" "$@"
