#!/usr/bin/env bash
# 3D-parallel smoke: proves the TrainEngine trains over a dp2×fsdp2×tp2
# mesh of 8 virtual CPU devices via the SpecLayout table
# (distributed/layout.py) with in-step remat + microbatch accumulation.
#
# Trains a small GPT both ways and asserts
#   * per-step losses on the 3D layout mesh (layout=True,
#     recompute="dots", accum_steps=2) match the replicated dp=8 run to
#     float32 ULP scale — sharding relocates the math, it must not
#     change it,
#   * the partitioned step's HLO carries the fsdp param collectives
#     (all-gather or reduce-scatter) AND the dp grad all-reduce,
#   * per-device step memory (XLA memory_analysis: temp+argument bytes
#     of the compiled engine step) shrinks vs the replicated dp=8 step —
#     the ZeRO param/opt sharding claim, and
#   * the process exits clean (rc=0).
# Then runs the mesh3d-marked pytest suite.  Extra args pass to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

python - <<'EOF'
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import TrainEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

V, S, B, STEPS = 512, 64, 8, 3
MESH3D = {"dp": 2, "fsdp": 2, "tp": 2}


def lm_loss(logits, labels):
    import jax
    import jax.numpy as jnp

    lv = logits.value if hasattr(logits, "value") else logits
    yv = labels.value if hasattr(labels, "value") else labels
    logp = jax.nn.log_softmax(lv[:, :-1].astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, yv[:, 1:, None], axis=-1).mean()


def build():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=V, hidden_size=256, num_layers=2,
                    num_heads=4, max_position_embeddings=S,
                    dropout=0.0, attn_dropout=0.0)
    net = GPTForCausalLM(cfg)
    model = paddle.Model(net)
    # small lr: a stable trajectory — training chaos amplifies per-step
    # ULP divergence exponentially, which would test the model's
    # conditioning, not the layout's sharding
    model.prepare(
        paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                               parameters=net.parameters()),
        lm_loss)
    return model


def batch(i=0):
    rs = np.random.RandomState(7 + i)
    return paddle.to_tensor(rs.randint(0, V, (B, S)).astype(np.int32))


def losses(mesh, **begin_kw):
    model = build()
    eng = TrainEngine(model).begin(mesh=mesh, **begin_kw)
    model.network.train()
    for i in range(STEPS):
        ids = batch(i)
        eng.step([ids], [ids])
    out = eng.drain()
    eng.finish()
    return out


def step_info(mesh, **begin_kw):
    """Compiled engine step: (HLO text, per-device temp+argument bytes).
    memory_analysis is PER-DEVICE for SPMD modules — exactly the ZeRO
    claim under test."""
    model = build()
    eng = TrainEngine(model).begin(mesh=mesh, **begin_kw)
    ids = batch()
    c = eng.lower_step([ids], [ids]).compile()
    eng.finish()
    ma = c.memory_analysis()
    ma = ma[0] if isinstance(ma, (list, tuple)) else ma
    mem = (ma.temp_size_in_bytes + ma.argument_size_in_bytes
           if ma is not None else None)
    return c.as_text(), mem


l_dp = losses({"dp": 8})
l_3d = losses(MESH3D, layout=True, recompute="dots", accum_steps=2)
print(f"[mesh3d_smoke] dp=8 per-step losses: {l_dp}")
print(f"[mesh3d_smoke] 3D   per-step losses: {l_3d}")
np.testing.assert_allclose(l_dp, l_3d, rtol=2e-5, atol=1e-6)
assert all(np.isfinite(l_3d)), l_3d
print("[mesh3d_smoke] dp2xfsdp2xtp2 (layout + remat + accum=2) matches "
      "dp=8 to float32 ULP scale")

hlo_dp, mem_dp = step_info({"dp": 8})
hlo_3d, mem_3d = step_info(MESH3D, layout=True, recompute="dots")
assert "all-gather" in hlo_3d or "reduce-scatter" in hlo_3d, \
    "fsdp param collectives missing from partitioned 3D step"
assert "all-reduce" in hlo_3d, "dp grad sync missing from partitioned step"
print("[mesh3d_smoke] HLO carries fsdp all-gather/reduce-scatter + dp "
      "all-reduce")

if mem_dp is None or mem_3d is None:
    print("[mesh3d_smoke] WARNING: backend reports no memory_analysis; "
          "grad-memory-reduction assert skipped")
else:
    ratio = mem_3d / mem_dp
    print(f"[mesh3d_smoke] per-device step memory: dp8={mem_dp / 2**20:.1f} "
          f"MiB  3D={mem_3d / 2**20:.1f} MiB  (ratio {ratio:.3f})")
    assert ratio < 0.6, (
        f"ZeRO param/opt sharding should shrink per-device step memory "
        f"well below the replicated dp8 step; got ratio {ratio:.3f}")
EOF
echo "[mesh3d_smoke] 3D-parallel engine OK"

exec python -m pytest tests/ -q -m mesh3d \
    -p no:cacheprovider -p no:randomly "$@"
