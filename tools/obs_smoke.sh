#!/usr/bin/env bash
# Observability smoke (ISSUE 6 acceptance): run a short bert-style fit
# with the monitor enabled and prove the whole telemetry surface end to
# end —
#   * a live /metrics endpoint reporting nonzero, sane paddle_train_mfu
#     and paddle_train_step_ms histograms scraped MID-FIT,
#   * /debug/trace?steps=3 armed over HTTP against the running job
#     produces jax.profiler trace artifacts,
#   * SIGUSR1 mid-fit arms a second bounded capture that completes,
#   * checkpoint stall timings land in the registry,
#   * the JSONL event log exists and parses,
#   * a traced concurrent-generate burst yields complete span trees on
#     /debug/spans (queue + prefill + decode covering the request wall
#     time) and a perfetto-loadable chrome export,
#   * a chaos-stalled trainer killed by the watchdog (exit 86) leaves a
#     valid flight-recorder dump that the goodput ledger ingests,
#   * monitor overhead on the smoke step time stays within budget
#     (OBS_OVERHEAD_PCT, default 2%) with tracing on at the default
#     sample rate, measured as alternating monitor-off/monitor-on
#     steady-state fits in one process,
# then runs the `monitor` + `trace` pytest suites.  Extra args pass to
# pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
WORK="$(mktemp -d /tmp/paddle_obs_smoke.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT
export OBS_WORK="$WORK"
export OBS_OVERHEAD_PCT="${OBS_OVERHEAD_PCT:-2}"

echo "== obs_smoke: live fit + scrape + trace + SIGUSR1 =="
python - <<'EOF'
import json, os, signal, threading, time, urllib.request

work = os.environ["OBS_WORK"]
tdir = os.path.join(work, "telemetry")

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework import flags
from paddle_tpu import monitor

flags.set_flags({"FLAGS_telemetry_dir": tdir, "FLAGS_monitor_port": 0})

# bert-smoke-shaped model (the bench smoke encoder, scaled to seconds)
L, H, A, I, S, B, V = 2, 64, 4, 128, 32, 8, 500
paddle.seed(0)

class Bert(nn.Layer):
    def __init__(self):
        super().__init__()
        self.embed = nn.Embedding(V, H)
        layer = nn.TransformerEncoderLayer(H, A, I, dropout=0.0,
                                           activation="gelu")
        self.encoder = nn.TransformerEncoder(layer, L)
        self.head = nn.Linear(H, V)

    def forward(self, ids):
        return self.head(self.encoder(self.embed(ids)))

rs = np.random.RandomState(0)
N = 320  # 40 steps of batch 8 per epoch (epochs below give the prober
         # enough runway to act on the RUNNING job)
x = rs.randint(0, V, (N, S)).astype("int64")
y = rs.randint(0, V, (N, S)).astype("int64")
ds = paddle.io.TensorDataset([x, y])

net = Bert()
model = paddle.Model(net)
model.prepare(paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=net.parameters()),
              nn.CrossEntropyLoss())

results = {}
def prober():
    # wait for the monitor endpoint, then act on the RUNNING job
    srv = None
    for _ in range(300):
        srv = monitor.get_monitor_server()
        if srv is not None:
            break
        time.sleep(0.05)
    assert srv is not None, "monitor server never came up"
    url = srv.url
    # poll mid-fit until the MFU gauge goes live (the first window can
    # land only after the first-step compile finishes)
    body = ""
    for _ in range(300):
        body = urllib.request.urlopen(url + "/metrics",
                                      timeout=5).read().decode()
        for line in body.splitlines():
            if line.startswith("paddle_train_mfu ") \
                    and float(line.split()[1]) > 0:
                break
        else:
            time.sleep(0.2)
            continue
        break
    results["midfit_metrics"] = body

    def traces_done():
        b = urllib.request.urlopen(url + "/metrics",
                                   timeout=5).read().decode()
        for line in b.splitlines():
            if line.startswith("paddle_train_traces_total "):
                return float(line.split()[1])
        return 0.0

    results["trace"] = json.loads(urllib.request.urlopen(
        url + "/debug/trace?steps=3", timeout=5).read())
    # wait for the HTTP-armed capture to COMPLETE before sending the
    # signal (a SIGUSR1 during an active capture extends it instead of
    # starting a second one)
    for _ in range(300):
        if traces_done() >= 1:
            break
        time.sleep(0.1)
    os.kill(os.getpid(), signal.SIGUSR1)  # headless equivalent

t = threading.Thread(target=prober, daemon=True)
t.start()
model.fit(ds, batch_size=B, epochs=4, log_freq=5, verbose=0,
          resume=os.path.join(work, "ckpt"),
          save_dir=os.path.join(work, "ckpt"), checkpoint_interval=10)
t.join(30)
assert not t.is_alive(), "prober never finished"

body = results["midfit_metrics"]
def metric_value(name, text):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"{name} not in /metrics")

mfu = metric_value("paddle_train_mfu", body)
assert 0.0 < mfu <= 1.5, f"paddle_train_mfu insane: {mfu}"
assert "paddle_train_step_ms_bucket" in body, "step-time histogram missing"
assert metric_value("paddle_train_step_ms_count", body) > 0
print(f"  mid-fit scrape ok: mfu={mfu}, "
      f"steps={metric_value('paddle_train_step_ms_count', body):.0f}")

# final state: both captures completed, artifacts on disk
telem, srv = monitor.fit_monitor()
final = urllib.request.urlopen(srv.url + "/metrics", timeout=5).read().decode()
assert metric_value("paddle_train_traces_total", final) >= 2, \
    "HTTP-armed + SIGUSR1 captures did not both complete"
assert metric_value("paddle_ckpt_step_stall_ms_count", final) >= 1, \
    "checkpoint stall timings missing"

def files_under(root):
    return [os.path.join(b, f) for b, _d, fs in os.walk(root) for f in fs]

assert files_under(results["trace"]["trace_dir"]), \
    f"/debug/trace produced no artifacts in {results['trace']['trace_dir']}"
print(f"  trace artifacts: {len(files_under(results['trace']['trace_dir']))} "
      f"file(s) in {results['trace']['trace_dir']}")

events = [json.loads(l) for l in open(os.path.join(tdir, "events.jsonl"))]
kinds = {e["event"] for e in events}
assert {"fit_begin", "window", "trace_begin", "trace_end", "ckpt",
        "fit_end"} <= kinds, f"event log incomplete: {kinds}"
windows = [e for e in events if e["event"] == "window"]
assert all(w["samples_per_sec"] > 0 for w in windows)
print(f"  event log ok: {len(events)} events, {len(windows)} windows")
monitor.reset()
print("LIVE-FIT OK")
EOF

echo "== obs_smoke: traced generate burst + flight recorder + goodput =="
python - <<'EOF'
import json, os, subprocess, sys, threading, urllib.request

work = os.environ["OBS_WORK"]

import paddle_tpu as paddle
from paddle_tpu.framework import flags
from paddle_tpu import monitor
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.monitor import MonitorServer
from paddle_tpu.serving.client import ServingClient
from paddle_tpu.serving.generation import GenerationEngine
from paddle_tpu.serving.server import ServingServer

# -- 1. traced concurrent-generate burst -> /debug/spans ----------------
flags.set_flags({"FLAGS_trace_sample_rate": 1.0})
monitor.reset()
paddle.seed(0)
cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                max_position_embeddings=64, dropout=0.0, attn_dropout=0.0)
model = GPTForCausalLM(cfg)
model.eval()
eng = GenerationEngine(model, max_slots=2, max_seq_len=32,
                       prompt_buckets="8")
srv = ServingServer(None, gen_engine=eng,
                    install_signal_handlers=False).start()
try:
    client = ServingClient(srv.url)
    outs = []
    def burst(i):
        outs.append(client.generate([1 + i, 2, 3], max_new_tokens=4))
    threads = [threading.Thread(target=burst, args=(i,)) for i in range(4)]
    for t in threads: t.start()
    for t in threads: t.join()
    assert len(outs) == 4 and all(len(o["tokens"]) >= 1 for o in outs)

    with MonitorServer(port=0) as mon:
        doc = json.loads(urllib.request.urlopen(
            mon.url + "/debug/spans", timeout=5).read())
        chrome = json.loads(urllib.request.urlopen(
            mon.url + "/debug/spans?format=chrome", timeout=5).read())
    assert chrome["traceEvents"], "chrome export empty"
    by_trace = {}
    for s in doc["spans"]:
        by_trace.setdefault(s["trace_id"], {})[s["name"]] = s
    complete = 0
    for tree in by_trace.values():
        need = {"server.generate", "gen.queued", "gen.prefill", "gen.decode"}
        if not need <= set(tree):
            continue
        total = sum(tree[n]["dur_ms"] for n in
                    ("gen.queued", "gen.prefill", "gen.decode"))
        wall = tree["server.generate"]["dur_ms"]
        assert 0.5 * wall <= total <= 1.1 * wall, \
            f"queue+prefill+decode={total:.1f}ms vs request {wall:.1f}ms"
        complete += 1
    assert complete >= 1, f"no complete span tree in {len(by_trace)} traces"
    print(f"  span trees ok: {complete}/{len(by_trace)} complete, "
          f"{len(chrome['traceEvents'])} chrome events")
finally:
    srv.shutdown()
    monitor.reset()
    flags.set_flags({"FLAGS_trace_sample_rate": 0.01})

# -- 2. chaos watchdog exit 86 -> flight-recorder dump ------------------
fdir = os.path.join(work, "flightrec")
script = f"""
import time
from paddle_tpu.monitor import flightrec
from paddle_tpu.utils.metrics import default_registry
from paddle_tpu.distributed.resilience import ResilientRunner
flightrec.configure({fdir!r}); flightrec.install_hooks()
h_step = default_registry().histogram(
    "paddle_train_step_ms", "per-step wall time",
    [1, 2, 5, 10, 20, 50, 100, 250, 500, 1000, 5000, 30000])
def step(i, s):
    t0 = time.perf_counter()
    flightrec.record("step", step=i)
    time.sleep(0.02)
    h_step.observe((time.perf_counter() - t0) * 1e3)
    return s, 0.1
ResilientRunner(watchdog_timeout=0.5).run(step, {{}}, num_steps=10)
"""
env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
env.update({"JAX_PLATFORMS": "cpu", "PADDLE_CHAOS_SLOW_STEP": "3",
            "PADDLE_CHAOS_SLOW_SECONDS": "30"})
r = subprocess.run([sys.executable, "-c", script], env=env,
                   capture_output=True, text=True, timeout=120)
assert r.returncode == 86, f"expected exit 86, got {r.returncode}:\n{r.stderr[-2000:]}"
dumps = [f for f in os.listdir(fdir) if f.startswith("flightrec-")]
assert len(dumps) == 1, dumps
doc = json.load(open(os.path.join(fdir, dumps[0])))
assert doc["reason"] == "watchdog" and doc["records"], doc.get("reason")
print(f"  watchdog dump ok: {dumps[0]} reason={doc['reason']} "
      f"records={len(doc['records'])}")

# -- 3. the goodput ledger ingests the dump -----------------------------
from paddle_tpu.distributed.goodput import GoodputLedger
led = GoodputLedger(fdir)
totals = led.publish()
assert sum(totals.values()) > 0, totals
assert 0.0 <= led.ratio() <= 1.0
print(f"  goodput ledger ok: ratio={led.ratio():.3f} "
      f"seconds={ {k: round(v, 2) for k, v in totals.items()} }")
print("TRACING+FLIGHTREC OK")
EOF

echo "== obs_smoke: monitor overhead budget (<= ${OBS_OVERHEAD_PCT}%) =="
python - <<'EOF'
import os, time
work = os.environ["OBS_WORK"]
budget = float(os.environ["OBS_OVERHEAD_PCT"])

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework import flags
from paddle_tpu import monitor

L, H, A, I, S, B, V = 2, 64, 4, 128, 32, 8, 500
paddle.seed(0)

class Bert(nn.Layer):
    def __init__(self):
        super().__init__()
        self.embed = nn.Embedding(V, H)
        layer = nn.TransformerEncoderLayer(H, A, I, dropout=0.0,
                                           activation="gelu")
        self.encoder = nn.TransformerEncoder(layer, L)
        self.head = nn.Linear(H, V)

    def forward(self, ids):
        return self.head(self.encoder(self.embed(ids)))

rs = np.random.RandomState(0)
N = 1280  # 160 steps: per-fit fixed costs (telemetry singleton, JSONL
          # open, engine begin) amortize out of the per-STEP number the
          # acceptance pins
x = rs.randint(0, V, (N, S)).astype("int64")
y = rs.randint(0, V, (N, S)).astype("int64")
ds = paddle.io.TensorDataset([x, y])
net = Bert()
model = paddle.Model(net)
model.prepare(paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=net.parameters()),
              nn.CrossEntropyLoss())

OFF = {"FLAGS_telemetry_dir": "", "FLAGS_monitor_port": -1,
       "FLAGS_trace_sample_rate": 0.0}
ON = {"FLAGS_telemetry_dir": os.path.join(work, "telem_overhead"),
      "FLAGS_monitor_port": -1,  # JSONL+metrics on; HTTP not the hot path
      "FLAGS_trace_sample_rate": 0.01}  # tracing at its DEFAULT rate —
#     the overhead pin covers the span tracer + flight recorder too

def timed_fit():
    t0 = time.perf_counter()
    model.fit(ds, batch_size=B, epochs=1, shuffle=False, verbose=0)
    return time.perf_counter() - t0

flags.set_flags(OFF)
timed_fit()  # compile + warmup, excluded
# telemetry warmup too (creates the singleton + one ensure_flops compile)
flags.set_flags(ON); timed_fit()
off, on = [], []
for _ in range(5):  # alternate to cancel machine drift; 5 rounds so a
    # single quiet-machine outlier on ONE side can't fake an overhead
    # (min-of-3 lost to a lone fast OFF fit on a noisy box)
    flags.set_flags(OFF); off.append(timed_fit())
    flags.set_flags(ON);  on.append(timed_fit())
flags.set_flags(OFF)
monitor.reset()
overhead = (min(on) - min(off)) / min(off) * 100.0
print(f"  steady-state fit: off={min(off)*1e3:.1f}ms "
      f"on={min(on)*1e3:.1f}ms overhead={overhead:+.2f}%")
assert overhead <= budget, \
    f"monitor overhead {overhead:.2f}% exceeds {budget}% budget"
print("OVERHEAD OK")
EOF

echo "== obs_smoke: perf-regression gate =="
# one bert smoke bench vs the committed noise-banded baseline
# (tools/perf_baseline.json); PADDLE_SKIP_PERF_GATE=1 skips
if [ "${PADDLE_SKIP_PERF_GATE:-0}" != "1" ]; then
    python bench.py --config bert > "$WORK/bench_bert.jsonl"
    python tools/perf_gate.py --run "$WORK/bench_bert.jsonl" --subset \
        || { echo "obs_smoke: perf gate FAILED"; exit 1; }
fi

echo "== obs_smoke: monitor + trace + perf pytest suites =="
python -m pytest tests/test_monitor.py tests/test_profiler.py \
    tests/test_tracing.py tests/test_perf.py -q -m "not slow" \
    -p no:cacheprovider "$@"

echo "obs_smoke: ALL OK"
