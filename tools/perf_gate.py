#!/usr/bin/env python3
"""Noise-aware perf-regression gate over bench.py JSONL output.

Compares one or more bench runs against the committed baseline
(tools/perf_baseline.json) and exits nonzero when a baseline-known
metric regressed beyond its noise band or went missing.  Stdlib-only
and jax-free: importing bench.py pulls no jax, and GATE_METRICS there
is the single source of metric directions and default noise bands.

Noise handling:
  * min-of-N — pass several --run files (or one file with repeated
    runs of the same config); per metric the gate keeps the BEST
    observation (min for lower-better, max for higher-better), so a
    single noisy sample can't fail a healthy build.
  * relative thresholds — each baseline entry stores rel_tol, chosen
    at --write-baseline time from GATE_METRICS (wide cpu_rel_tol on
    CPU, tighter tpu_rel_tol on TPU) and hand-editable afterwards.
  * zero baselines compare exact (a 0.0 device_mem_peak_mb on CPU
    stays 0.0; any nonzero best still passes with a warning since
    there is no ratio to band).

Policy:
  * config or metric present in the RUN but not in the baseline →
    warning only (new configs/metrics are adopted via the ratchet).
  * metric present in the BASELINE but missing/null/errored in every
    run → FAIL (a metric that silently vanishes is a regression).
  * --write-baseline ratchets: existing entries only move in the
    improving direction (use --force to reset after an accepted
    regression, e.g. a feature that legitimately costs memory).

Usage:
  python tools/perf_gate.py --run bench_out.jsonl [--run more.jsonl]
  python tools/perf_gate.py --run bench_out.jsonl --write-baseline
  PADDLE_SKIP_PERF_GATE=1 ...   # smokes honor this and skip the gate

Exit codes: 0 pass, 1 regression/missing metric, 2 usage/schema error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from bench import BENCH_SCHEMA_VERSION, GATE_METRICS  # noqa: E402

BASELINE_DEFAULT = os.path.join(_REPO, "tools", "perf_baseline.json")
# config lines only — infrastructure lines never carry gate metrics
_NON_CONFIG = {"bench_summary", "tpu_outage_diagnostic"}


def load_runs(paths):
    """Parse bench JSONL files into {config: {metric: [observations]}}
    plus the platform seen ('tpu' if ANY line ran on one)."""
    obs, platform, parsed = {}, "cpu", 0
    for path in paths:
        try:
            fh = sys.stdin if path == "-" else open(path)
        except OSError as e:
            raise SystemExit(f"perf_gate: cannot read run file: {e}")
        with fh:
            for raw in fh:
                raw = raw.strip()
                if not raw.startswith("{"):
                    continue
                try:
                    line = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                cfg = line.get("metric")
                if (not isinstance(line, dict) or not cfg
                        or cfg in _NON_CONFIG or line.get("partial")):
                    continue
                sv = line.get("schema_version")
                if sv is not None and sv > BENCH_SCHEMA_VERSION:
                    print(f"perf_gate: WARN {cfg}: line schema_version "
                          f"{sv} > gate's {BENCH_SCHEMA_VERSION}")
                parsed += 1
                if str(line.get("platform", "cpu")).lower() != "cpu":
                    platform = "tpu"
                bucket = obs.setdefault(cfg, {})
                for metric in GATE_METRICS:
                    val = line.get(metric)
                    if isinstance(val, (int, float)):
                        bucket.setdefault(metric, []).append(float(val))
    if not parsed:
        raise SystemExit("perf_gate: no config lines found in run file(s)")
    return obs, platform


def best_of(values, direction):
    return (max if direction == "higher" else min)(values)


def check(obs, baseline, subset=False):
    """Returns (failures, warnings) comparing best-of-N obs against the
    committed baseline.  With ``subset``, baseline configs absent from
    the run are skipped (a smoke that deliberately runs one config);
    without it a vanished config is a failure."""
    failures, warnings = [], []
    base_cfgs = baseline.get("configs", {})
    for cfg in obs:
        if cfg not in base_cfgs:
            warnings.append(f"{cfg}: not in baseline (ratchet to adopt)")
    for cfg, metrics in base_cfgs.items():
        got = obs.get(cfg, {})
        if not got:
            if subset:
                continue
            failures.append(f"{cfg}: config missing from run "
                            "(errored or removed)")
            continue
        for metric, spec in metrics.items():
            direction = spec.get("direction", "lower")
            base = float(spec["value"])
            tol = float(spec.get("rel_tol", 0.25))
            vals = got.get(metric)
            if not vals:
                failures.append(f"{cfg}.{metric}: in baseline but "
                                "missing/null in every run")
                continue
            abs_tol = float(spec.get("abs_tol", 0.0))
            best = best_of(vals, direction)
            if base == 0.0 and abs_tol == 0.0:
                if best != 0.0:
                    warnings.append(f"{cfg}.{metric}: baseline 0, "
                                    f"measured {best:g} (no band; "
                                    "re-ratchet to adopt)")
                continue
            if direction == "higher":
                limit = max(0.0, base * (1.0 - tol) - abs_tol)
                bad = best < limit
            else:
                limit = base * (1.0 + tol) + abs_tol
                bad = best > limit
            if bad:
                failures.append(
                    f"{cfg}.{metric}: best-of-{len(vals)} {best:g} vs "
                    f"baseline {base:g} (allowed {'>=' if direction == 'higher' else '<='} "
                    f"{limit:g}, rel_tol {tol:g})")
    return failures, warnings


def write_baseline(obs, platform, path, old, force):
    tol_key = "tpu_rel_tol" if platform == "tpu" else "cpu_rel_tol"
    old_cfgs = old.get("configs", {}) if not force else {}
    configs = {}
    for cfg, metrics in sorted(obs.items()):
        entry = {}
        for metric, vals in sorted(metrics.items()):
            spec = GATE_METRICS[metric]
            direction = spec["direction"]
            best = best_of(vals, direction)
            prev = old_cfgs.get(cfg, {}).get(metric)
            if prev is not None:
                # ratchet: keep the better of old and new so a noisy
                # lucky/unlucky re-baseline can't loosen the gate
                best = best_of([best, float(prev["value"])], direction)
            entry[metric] = {
                "value": round(best, 6),
                "direction": direction,
                "rel_tol": (prev or {}).get("rel_tol", spec[tol_key]),
            }
            abs_default = spec.get(f"{platform}_abs_tol", 0.0)
            abs_tol = (prev or {}).get("abs_tol", abs_default)
            if abs_tol:
                entry[metric]["abs_tol"] = abs_tol
        if entry:
            configs[cfg] = entry
    doc = {"schema_version": BENCH_SCHEMA_VERSION, "platform": platform,
           "configs": configs}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return doc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--run", action="append", required=True,
                    help="bench JSONL output file ('-' for stdin); "
                         "repeat for min-of-N across runs")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT)
    ap.add_argument("--write-baseline", action="store_true",
                    help="ratchet the baseline from this run instead "
                         "of gating against it")
    ap.add_argument("--force", action="store_true",
                    help="with --write-baseline: overwrite instead of "
                         "ratcheting (accept a regression)")
    ap.add_argument("--subset", action="store_true",
                    help="gate only the configs present in the run "
                         "(for smokes that deliberately run a subset); "
                         "a missing config is otherwise a failure")
    args = ap.parse_args(argv)

    obs, platform = load_runs(args.run)

    old = {}
    if os.path.exists(args.baseline):
        try:
            with open(args.baseline) as fh:
                old = json.load(fh)
        except (OSError, ValueError) as e:
            raise SystemExit(f"perf_gate: unreadable baseline: {e}")

    if args.write_baseline:
        doc = write_baseline(obs, platform, args.baseline, old, args.force)
        n = sum(len(m) for m in doc["configs"].values())
        print(f"perf_gate: baseline written to {args.baseline} "
              f"({len(doc['configs'])} configs, {n} metrics, "
              f"platform={platform})")
        return 0

    if not old:
        print(f"perf_gate: no baseline at {args.baseline} — run with "
              "--write-baseline first (pass)")
        return 0
    if old.get("platform", "cpu") != platform:
        print(f"perf_gate: WARN baseline platform "
              f"{old.get('platform')!r} != run platform {platform!r} — "
              "bands may not fit; re-baseline on this platform")

    failures, warnings = check(obs, old, subset=args.subset)
    for w in warnings:
        print(f"perf_gate: WARN {w}")
    if failures:
        for f in failures:
            print(f"perf_gate: FAIL {f}")
        print(f"perf_gate: {len(failures)} regression(s) vs "
              f"{args.baseline}")
        return 1
    n = sum(len(m) for m in old.get("configs", {}).values())
    print(f"perf_gate: PASS ({n} baseline metrics within bands)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
