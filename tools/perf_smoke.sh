#!/usr/bin/env bash
# Perf smoke: proves the persistent XLA compilation cache
# (FLAGS_jit_cache_dir) works process-over-process, then runs the
# perf-marked pytest suite.
#
# Runs the bert and ernie CPU smoke benches TWICE each in fresh
# processes against a fresh cache directory and asserts the second
# process's compile time drops (the first process pays XLA, the second
# reads the executable from disk).  Exits non-zero on any regression.
# Extra args are passed through to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
CACHE_DIR="$(mktemp -d /tmp/paddle_perf_cache.XXXXXX)"
OUT_DIR="$(mktemp -d /tmp/paddle_perf_out.XXXXXX)"
trap 'rm -rf "$CACHE_DIR" "$OUT_DIR"' EXIT
export FLAGS_JIT_CACHE_DIR="$CACHE_DIR"       # flags.py env override
export FLAGS_JIT_CACHE_MIN_COMPILE_SECS=0     # cache every executable

compile_seconds() {  # run one bench config, print its compile_seconds
    local out="$OUT_DIR/bench_$1_$RANDOM.out"
    python bench.py --config "$1" > "$out"
    python - "$out" <<'EOF'
import json, sys
last = None
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{") and '"compile_seconds"' in line:
        last = json.loads(line)
if last is None:
    sys.exit("no compile_seconds in bench output")
print(last["compile_seconds"])
EOF
}

fail=0
for cfg in bert ernie; do
    c1=$(compile_seconds "$cfg")
    c2=$(compile_seconds "$cfg")
    echo "[perf_smoke] $cfg compile: first=${c1}s second=${c2}s"
    python - "$cfg" "$c1" "$c2" <<'EOF' || fail=1
import sys
cfg, c1, c2 = sys.argv[1], float(sys.argv[2]), float(sys.argv[3])
# the second process must at least not pay the full compile again; the
# 0.8 factor absorbs trace/dispatch noise on tiny CPU smoke graphs
if not (c2 < c1 and c2 < c1 * 0.8):
    sys.exit(f"{cfg}: persistent compile cache did not help "
             f"({c1:.2f}s -> {c2:.2f}s)")
print(f"{cfg}: cache hit OK ({c1:.2f}s -> {c2:.2f}s)")
EOF
done
[ "$(ls -A "$CACHE_DIR")" ] || { echo "cache dir is empty"; fail=1; }
[ "$fail" -eq 0 ] || { echo "[perf_smoke] FAILED"; exit 1; }

# perf-regression gate over the four bench runs above (min-of-N per
# metric) vs the committed baseline; PADDLE_SKIP_PERF_GATE=1 skips
if [ "${PADDLE_SKIP_PERF_GATE:-0}" != "1" ]; then
    gate_args=()
    for out in "$OUT_DIR"/bench_*.out; do gate_args+=(--run "$out"); done
    python tools/perf_gate.py "${gate_args[@]}" \
        || { echo "[perf_smoke] perf gate FAILED"; exit 1; }
fi

exec python -m pytest tests/ -q -m perf \
    -p no:cacheprovider -p no:randomly "$@"
