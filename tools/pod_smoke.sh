#!/usr/bin/env bash
# Pod smoke: proves the elastic pod runtime shrinks-and-continues through
# a REAL rank loss (distributed/elastic.py + podcoord.py).
#
# Launches a 2-rank local pod under the shrink-and-continue supervisor,
# SIGKILLs rank 1 mid-fit via chaos (PADDLE_CHAOS_RANK_KILL), and asserts
#   * the survivor detects the death, rolls back to its in-memory
#     snapshot, re-strides the batch, replays, and FINISHES (rc 0),
#   * the death is classified rank_lost_shrunk (not crash) in
#     paddle_launch_trainer_failures_total,
#   * the goodput ledger's badput{down} for the in-memory continue beats
#     a restart-from-checkpoint equivalent measured in this same script
#     (the restart path's FLOOR: fresh interpreter + framework import,
#     before any restore/fast-forward even starts), and
#   * the SIGKILLed rank still left attributable JSONL telemetry.
# Then runs the pod-marked pytest suite (units + every multi-process
# drill).  Extra args pass through to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu

python - <<'EOF'
import json
import os
import subprocess
import sys
import tempfile
import time

from paddle_tpu.distributed.podcoord import DEAD_EXIT
from paddle_tpu.distributed.podtest import run_elastic_pod
from paddle_tpu.utils.metrics import default_registry

SRC = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed.elastic import PodRuntime
from paddle_tpu.io import TensorDataset

paddle.seed(0)
net = paddle.nn.Linear(16, 8)
model = paddle.Model(net)
model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters()),
              paddle.nn.MSELoss())
rs = np.random.RandomState(0)
x = rs.randn(96, 16).astype("float32")
y = rs.randn(96, 8).astype("float32")
pod = PodRuntime.from_env()
model.fit(TensorDataset([x, y]), batch_size=8, epochs=1, shuffle=False,
          verbose=0, pod=pod, log_freq=1)
emit(shrinks=pod.shrink_events, live=pod.live)
pod.close()
"""

with tempfile.TemporaryDirectory(prefix="pod-smoke-") as td:
    res, pr = run_elastic_pod(
        SRC, world=2, env={"PADDLE_CHAOS_RANK_KILL": "1@3"},
        telemetry_dir=td, timeout=300)

    # rank 1 really died by SIGKILL; the survivor finished from memory
    assert res.returncodes == [0, -9], res.returncodes
    assert res.survivors_ok, (res.returncodes, res.deaths)
    assert res.deaths[1][0] == DEAD_EXIT, res.deaths
    shrinks = pr.record(0, "shrinks")
    assert shrinks and shrinks[-1]["live"] == [0], shrinks
    print(f"[pod_smoke] rank 1 SIGKILLed mid-fit; rank 0 shrank "
          f"{shrinks[-1]['old']} -> {shrinks[-1]['live']} and finished "
          f"(recovery {shrinks[-1]['recovery_s']:.3f}s)")

    # the death was accounted as rank_lost_shrunk, not a pod crash
    c = default_registry().get("paddle_launch_trainer_failures_total")
    assert c is not None and c.get("rank_lost_shrunk") >= 1, (
        c and c.collect())

    # the SIGKILLed rank still left JSONL telemetry for attribution
    ev1 = os.path.join(td, "rank1", "events.jsonl")
    assert os.path.exists(ev1), os.listdir(td)

    # goodput: in-memory continue's badput{down} vs the restart path's
    # FLOOR (fresh interpreter + framework import, measured here; a real
    # restart also pays checkpoint restore + step fast-forward on top)
    assert res.report is not None
    down_s = res.report["seconds"].get("down", 0.0)
    assert down_s > 0, res.report
    t0 = time.perf_counter()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    subprocess.run([sys.executable, "-c", "import jax, paddle_tpu"],
                   env=env, timeout=300, check=True,
                   capture_output=True)
    restart_floor_s = time.perf_counter() - t0
    assert down_s < restart_floor_s, (down_s, restart_floor_s)
    print(f"[pod_smoke] badput down={down_s:.3f}s beats the "
          f"restart-equivalent floor {restart_floor_s:.2f}s "
          f"(goodput_ratio={res.report['goodput_ratio']})")
    print("[pod_smoke] " + json.dumps(
        {"elastic_shrink_recovery_s": res.recovery_s(),
         "badput_down_s": round(down_s, 4),
         "restart_equivalent_s": round(restart_floor_s, 2),
         "goodput_ratio": res.report["goodput_ratio"]}))
EOF
echo "[pod_smoke] elastic shrink-and-continue drill OK"

exec python -m pytest tests/ -q -m pod \
    -p no:cacheprovider -p no:randomly "$@"
