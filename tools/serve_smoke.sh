#!/usr/bin/env bash
# Serving smoke: proves the paddle_tpu.serving stack end-to-end on CPU —
# export a model, start the HTTP server, fire concurrent requests via
# serving/client.py, scrape /metrics and assert the qps and p99 fields
# are present and sane, then SIGTERM the server and require a clean
# graceful drain (exit 0).  Then the same contract for the continuous-
# batching generation server: N parallel streaming /generate clients,
# inter-token p99 asserted from /metrics, compile count proven FLAT
# across a second load burst (zero recompiles after warmup), SIGTERM
# drain.  Finishes by running the serving- and genserve-marked pytest
# suites.  Extra args are passed through to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
WORK="$(mktemp -d /tmp/paddle_serve_smoke.XXXXXX)"
SERVER_PID=""
R0_PID=""
R1_PID=""
ROUTER_PID=""
cleanup() {
    for pid in "$SERVER_PID" "$ROUTER_PID" "$R0_PID" "$R1_PID"; do
        [ -n "$pid" ] && kill -9 "$pid" 2>/dev/null || true
    done
    rm -rf "$WORK"
}
trap cleanup EXIT

wait_url() {  # $1=logfile $2=pid -> echoes url once the readiness line lands
    local url=""
    for _ in $(seq 1 600); do
        url=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' "$1" | head -1)
        [ -n "$url" ] && { echo "$url"; return 0; }
        kill -0 "$2" 2>/dev/null || return 1
        sleep 0.1
    done
    return 1
}

echo "[serve_smoke] exporting model..."
python - "$WORK" <<'EOF'
import sys

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import inference
from paddle_tpu.static import InputSpec

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                           paddle.nn.Linear(32, 4))
net.eval()
inference.save_inference_model(
    sys.argv[1] + "/mlp", net,
    input_spec=[InputSpec([-1, 8], "float32")],
    example_inputs=[np.zeros((2, 8), np.float32)])
print("exported", sys.argv[1] + "/mlp")
EOF

echo "[serve_smoke] starting server..."
python -m paddle_tpu.serving.server --model "$WORK/mlp" --port 0 \
    --max-batch 8 --timeout-ms 3 > "$WORK/server.log" 2>&1 &
SERVER_PID=$!

URL=""
for _ in $(seq 1 200); do
    URL=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' "$WORK/server.log" \
          | head -1)
    [ -n "$URL" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null \
        || { echo "server died:"; cat "$WORK/server.log"; exit 1; }
    sleep 0.1
done
[ -n "$URL" ] || { echo "server never came up"; cat "$WORK/server.log"; exit 1; }
echo "[serve_smoke] server up at $URL"

echo "[serve_smoke] firing load..."
python -m paddle_tpu.serving.client --url "$URL" --requests 40 \
    --concurrency 4 --shape 8 --dtype float32

echo "[serve_smoke] scraping /metrics..."
python - "$URL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_serving_qps", "paddle_serving_p99_ms",
          "paddle_serving_p50_ms", "paddle_serving_batch_size_bucket",
          "paddle_serving_queue_latency_ms_bucket",
          "paddle_serving_padding_waste_ratio"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing metrics: {missing}"


def value(name):
    line = [l for l in text.splitlines() if l.startswith(name + " ")][0]
    return float(line.split()[1])


qps, p99 = value("paddle_serving_qps"), value("paddle_serving_p99_ms")
assert qps > 0, f"qps not positive: {qps}"
assert p99 > 0, f"p99 not positive: {p99}"
compiles = value("paddle_serving_compile_count")
print(f"metrics OK: qps={qps:g} p99_ms={p99:g} bucket_compiles={compiles:g}")
EOF

echo "[serve_smoke] SIGTERM -> graceful drain..."
kill -TERM "$SERVER_PID"
rc=0
wait "$SERVER_PID" || rc=$?
SERVER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] server exit code $rc (want 0 = clean drain)"
    cat "$WORK/server.log"
    exit 1
fi
grep -q "serving drain clean" "$WORK/server.log" \
    || { echo "no clean-drain marker in server log"; cat "$WORK/server.log"; exit 1; }
echo "[serve_smoke] clean drain OK"

# ---- concurrent-decode section: continuous-batching generation --------
echo "[serve_smoke] starting generation server..."
python -m paddle_tpu.serving.generation --port 0 --slots 4 \
    --prompt-buckets 8,16 --max-seq-len 48 > "$WORK/genserver.log" 2>&1 &
SERVER_PID=$!

GURL=""
for _ in $(seq 1 600); do
    GURL=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' \
           "$WORK/genserver.log" | head -1)
    [ -n "$GURL" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null \
        || { echo "generation server died:"; cat "$WORK/genserver.log"; exit 1; }
    sleep 0.1
done
[ -n "$GURL" ] || { echo "generation server never came up"; \
    cat "$WORK/genserver.log"; exit 1; }
echo "[serve_smoke] generation server up at $GURL"

echo "[serve_smoke] firing concurrent streaming decode load..."
python -m paddle_tpu.serving.client --url "$GURL" --mode generate \
    --requests 12 --concurrency 6 --prompt-len 8 --max-new 16 \
    --vocab 200 --sample

echo "[serve_smoke] scraping genserve /metrics..."
COMPILES_1=$(python - "$GURL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_genserve_decode_tokens_per_sec",
          "paddle_genserve_ttft_p50_ms", "paddle_genserve_ttft_p99_ms",
          "paddle_genserve_inter_token_p50_ms",
          "paddle_genserve_inter_token_p99_ms",
          "paddle_genserve_slot_occupancy",
          "paddle_genserve_tokens_total",
          "paddle_genserve_compile_count"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing metrics: {missing}"


def value(name):
    line = [l for l in text.splitlines() if l.startswith(name + " ")][0]
    return float(line.split()[1])


tps = value("paddle_genserve_decode_tokens_per_sec")
it_p99 = value("paddle_genserve_inter_token_p99_ms")
ttft = value("paddle_genserve_ttft_p99_ms")
compiles = value("paddle_genserve_compile_count")
assert tps > 0, f"decode tokens/s not positive: {tps}"
assert 0 < it_p99 < 60_000, f"inter-token p99 insane: {it_p99}"
assert ttft > 0, f"ttft p99 not positive: {ttft}"
print(f"genserve metrics OK: tokens/s={tps:g} inter_token_p99_ms="
      f"{it_p99:g} ttft_p99_ms={ttft:g} compiles={compiles:g}",
      file=sys.stderr)
print(int(compiles))
EOF
)

echo "[serve_smoke] second burst (recompile check)..."
python -m paddle_tpu.serving.client --url "$GURL" --mode generate \
    --requests 8 --concurrency 4 --prompt-len 12 --max-new 10 \
    --vocab 200

COMPILES_2=$(python - "$GURL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
line = [l for l in text.splitlines()
        if l.startswith("paddle_genserve_compile_count ")][0]
print(int(float(line.split()[1])))
EOF
)
if [ "$COMPILES_1" != "$COMPILES_2" ]; then
    echo "[serve_smoke] RECOMPILE after warmup: $COMPILES_1 -> $COMPILES_2"
    exit 1
fi
echo "[serve_smoke] zero recompiles after warmup OK ($COMPILES_2 total)"

echo "[serve_smoke] SIGTERM -> generation graceful drain..."
kill -TERM "$SERVER_PID"
rc=0
wait "$SERVER_PID" || rc=$?
SERVER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] generation server exit code $rc (want 0)"
    cat "$WORK/genserver.log"
    exit 1
fi
grep -q "serving drain clean" "$WORK/genserver.log" \
    || { echo "no clean-drain marker in generation server log"; \
         cat "$WORK/genserver.log"; exit 1; }
echo "[serve_smoke] generation clean drain OK"

# ---- paged KV + prefix-cache section ----------------------------------
# an oversubscribed page pool (40 pages < 4 slots * 12 pages/slot) and
# the prefix cache on: every client prompt opens with the SAME 8-token
# system prefix (2 full 4-token pages), so after the first admission
# every admission is a prefix hit
echo "[serve_smoke] starting paged generation server (prefix cache on)..."
python -m paddle_tpu.serving.generation --port 0 --slots 4 \
    --prompt-buckets 8,16 --max-seq-len 48 --page-size 4 --num-pages 40 \
    --prefix-cache 1 > "$WORK/pagedserver.log" 2>&1 &
SERVER_PID=$!

PURL=""
for _ in $(seq 1 600); do
    PURL=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' \
           "$WORK/pagedserver.log" | head -1)
    [ -n "$PURL" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null \
        || { echo "paged server died:"; cat "$WORK/pagedserver.log"; exit 1; }
    sleep 0.1
done
[ -n "$PURL" ] || { echo "paged server never came up"; \
    cat "$WORK/pagedserver.log"; exit 1; }
echo "[serve_smoke] paged server up at $PURL"

echo "[serve_smoke] firing shared-system-prompt load..."
python -m paddle_tpu.serving.client --url "$PURL" --mode generate \
    --requests 12 --concurrency 6 --prompt-len 12 --shared-prefix-len 8 \
    --max-new 12 --vocab 200 --sample

echo "[serve_smoke] scraping paged /metrics..."
python - "$PURL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_genserve_prefix_cache_hits_total",
          "paddle_genserve_prefix_cache_misses_total",
          "paddle_genserve_prefix_cache_hit_ratio",
          "paddle_genserve_page_occupancy",
          "paddle_genserve_ttft_p99_ms"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing metrics: {missing}"


def value(name):
    line = [l for l in text.splitlines() if l.startswith(name + " ")][0]
    return float(line.split()[1])


ratio = value("paddle_genserve_prefix_cache_hit_ratio")
hits = value("paddle_genserve_prefix_cache_hits_total")
ttft = value("paddle_genserve_ttft_p99_ms")
assert ratio > 0, f"prefix hit ratio not positive under shared load: {ratio}"
assert hits > 0, f"no prefix hits under shared-prefix load: {hits}"
assert ttft > 0, f"ttft p99 not positive: {ttft}"
print(f"paged metrics OK: prefix_hit_ratio={ratio:g} hits={hits:g} "
      f"ttft_p99_ms={ttft:g}")
EOF

echo "[serve_smoke] SIGTERM -> paged graceful drain..."
kill -TERM "$SERVER_PID"
rc=0
wait "$SERVER_PID" || rc=$?
SERVER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] paged server exit code $rc (want 0)"
    cat "$WORK/pagedserver.log"
    exit 1
fi
grep -q "serving drain clean" "$WORK/pagedserver.log" \
    || { echo "no clean-drain marker in paged server log"; \
         cat "$WORK/pagedserver.log"; exit 1; }
echo "[serve_smoke] paged clean drain OK"

# ---- fleet router section ---------------------------------------------
# two SPECULATIVE replicas (1-layer derived draft, K=3) behind the
# prefix-aware router: a shared-prefix burst must ride the affinity
# table onto ONE replica (routed prefix_hit ratio at least as good as a
# single replica's own cache ratio), then the router drains clean
# before its replicas do
echo "[serve_smoke] starting 2 replica generation servers..."
python -m paddle_tpu.serving.generation --port 0 --slots 2 \
    --prompt-buckets 8,16 --max-seq-len 48 --page-size 4 --num-pages 40 \
    --prefix-cache 1 --draft-layers 1 --spec-tokens 3 \
    > "$WORK/replica0.log" 2>&1 &
R0_PID=$!
python -m paddle_tpu.serving.generation --port 0 --slots 2 \
    --prompt-buckets 8,16 --max-seq-len 48 --page-size 4 --num-pages 40 \
    --prefix-cache 1 --draft-layers 1 --spec-tokens 3 \
    > "$WORK/replica1.log" 2>&1 &
R1_PID=$!
R0_URL=$(wait_url "$WORK/replica0.log" "$R0_PID") \
    || { echo "replica0 never came up"; cat "$WORK/replica0.log"; exit 1; }
R1_URL=$(wait_url "$WORK/replica1.log" "$R1_PID") \
    || { echo "replica1 never came up"; cat "$WORK/replica1.log"; exit 1; }
echo "[serve_smoke] replicas up at $R0_URL $R1_URL"

echo "[serve_smoke] starting fleet router..."
python -m paddle_tpu.serving.router --replicas "$R0_URL,$R1_URL" \
    --port 0 --page-size 4 --probe-interval 0.2 \
    > "$WORK/router.log" 2>&1 &
ROUTER_PID=$!
RURL=$(wait_url "$WORK/router.log" "$ROUTER_PID") \
    || { echo "router never came up"; cat "$WORK/router.log"; exit 1; }
echo "[serve_smoke] router up at $RURL"

echo "[serve_smoke] firing shared-prefix burst through the router..."
python -m paddle_tpu.serving.client --url "$RURL" --mode generate \
    --requests 12 --concurrency 4 --prompt-len 12 --shared-prefix-len 8 \
    --max-new 10 --vocab 200

echo "[serve_smoke] scraping router /metrics (federated)..."
python - "$RURL" <<'EOF'
import re
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_router_requests_total", "paddle_router_replicas_healthy",
          "# replica=r0", "# replica=r1",
          "paddle_genserve_spec_accept_ratio"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing from federated metrics: {missing}"


def value(name, section):
    line = [l for l in section.splitlines()
            if l.startswith(name + " ")][0]
    return float(line.split()[1])


healthy = value("paddle_router_replicas_healthy",
                text.split("# replica=")[0])
assert healthy == 2, f"want 2 healthy replicas, got {healthy}"

routed = {}  # (replica, reason) -> count
for m in re.finditer(r'paddle_router_requests_total\{replica="([^"]+)",'
                     r'reason="([^"]+)"\} (\d+)', text):
    routed[(m.group(1), m.group(2))] = int(m.group(3))
total = sum(routed.values())
hit_owners = {r for (r, reason) in routed if reason == "prefix_hit"}
hits = sum(n for (r, reason), n in routed.items()
           if reason == "prefix_hit")
assert total == 12, f"want 12 routed requests, got {total}: {routed}"
assert len(hit_owners) == 1, \
    f"shared prefix must bind ONE replica, got {hit_owners}: {routed}"
assert hits >= 8, f"too few prefix_hit routes: {routed}"

# routed hit-ratio must be at least the owning replica's own cache
# ratio: affinity loses nothing vs pinning every request to one box
owner = hit_owners.pop()
section = [s for s in text.split("# replica=") if s.startswith(owner)][0]
replica_ratio = value("paddle_genserve_prefix_cache_hit_ratio", section)
router_ratio = hits / total
assert router_ratio + 1e-3 >= replica_ratio, \
    f"router hit-ratio {router_ratio} < replica's own {replica_ratio}"
print(f"router metrics OK: routed={routed} router_hit_ratio="
      f"{router_ratio:.3f} {owner}_cache_ratio={replica_ratio:g}")
EOF

echo "[serve_smoke] SIGTERM -> router drain, then replicas..."
kill -TERM "$ROUTER_PID"
rc=0
wait "$ROUTER_PID" || rc=$?
ROUTER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] router exit code $rc (want 0 = clean drain)"
    cat "$WORK/router.log"
    exit 1
fi
grep -q "router drain clean" "$WORK/router.log" \
    || { echo "no clean-drain marker in router log"; \
         cat "$WORK/router.log"; exit 1; }
for pid_var in R0_PID R1_PID; do
    pid=${!pid_var}
    kill -TERM "$pid"
    rc=0
    wait "$pid" || rc=$?
    eval "$pid_var=''"
    [ "$rc" -eq 0 ] || { echo "replica $pid_var exit code $rc (want 0)"; \
                         exit 1; }
done
grep -q "serving drain clean" "$WORK/replica0.log" \
    || { echo "no clean-drain marker in replica0 log"; exit 1; }
grep -q "serving drain clean" "$WORK/replica1.log" \
    || { echo "no clean-drain marker in replica1 log"; exit 1; }
echo "[serve_smoke] router + replica clean drain OK"

exec python -m pytest tests/ -q -m "serving or genserve or specdec" \
    -p no:cacheprovider -p no:randomly "$@"
