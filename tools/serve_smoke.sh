#!/usr/bin/env bash
# Serving smoke: proves the paddle_tpu.serving stack end-to-end on CPU —
# export a model, start the HTTP server, fire concurrent requests via
# serving/client.py, scrape /metrics and assert the qps and p99 fields
# are present and sane, then SIGTERM the server and require a clean
# graceful drain (exit 0).  Then the same contract for the continuous-
# batching generation server: N parallel streaming /generate clients,
# inter-token p99 asserted from /metrics, compile count proven FLAT
# across a second load burst (zero recompiles after warmup), SIGTERM
# drain.  Finishes by running the serving- and genserve-marked pytest
# suites.  Extra args are passed through to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
WORK="$(mktemp -d /tmp/paddle_serve_smoke.XXXXXX)"
SERVER_PID=""
R0_PID=""
R1_PID=""
ROUTER_PID=""
SUP_PID=""
cleanup() {
    for pid in "$SERVER_PID" "$ROUTER_PID" "$R0_PID" "$R1_PID"; do
        [ -n "$pid" ] && kill -9 "$pid" 2>/dev/null || true
    done
    if [ -n "$SUP_PID" ]; then
        # the supervisor owns replica subprocesses: TERM (latch-drain)
        # first so they are reaped, SIGKILL only as a last resort
        kill -TERM "$SUP_PID" 2>/dev/null || true
        for _ in $(seq 1 100); do
            kill -0 "$SUP_PID" 2>/dev/null || break
            sleep 0.1
        done
        kill -9 "$SUP_PID" 2>/dev/null || true
    fi
    rm -rf "$WORK"
}
trap cleanup EXIT

wait_url() {  # $1=logfile $2=pid -> echoes url once the readiness line lands
    local url=""
    for _ in $(seq 1 600); do
        url=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' "$1" | head -1)
        [ -n "$url" ] && { echo "$url"; return 0; }
        kill -0 "$2" 2>/dev/null || return 1
        sleep 0.1
    done
    return 1
}

echo "[serve_smoke] exporting model..."
python - "$WORK" <<'EOF'
import sys

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import inference
from paddle_tpu.static import InputSpec

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                           paddle.nn.Linear(32, 4))
net.eval()
inference.save_inference_model(
    sys.argv[1] + "/mlp", net,
    input_spec=[InputSpec([-1, 8], "float32")],
    example_inputs=[np.zeros((2, 8), np.float32)])
print("exported", sys.argv[1] + "/mlp")
EOF

echo "[serve_smoke] starting server..."
python -m paddle_tpu.serving.server --model "$WORK/mlp" --port 0 \
    --max-batch 8 --timeout-ms 3 > "$WORK/server.log" 2>&1 &
SERVER_PID=$!

URL=""
for _ in $(seq 1 200); do
    URL=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' "$WORK/server.log" \
          | head -1)
    [ -n "$URL" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null \
        || { echo "server died:"; cat "$WORK/server.log"; exit 1; }
    sleep 0.1
done
[ -n "$URL" ] || { echo "server never came up"; cat "$WORK/server.log"; exit 1; }
echo "[serve_smoke] server up at $URL"

echo "[serve_smoke] firing load..."
python -m paddle_tpu.serving.client --url "$URL" --requests 40 \
    --concurrency 4 --shape 8 --dtype float32

echo "[serve_smoke] scraping /metrics..."
python - "$URL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_serving_qps", "paddle_serving_p99_ms",
          "paddle_serving_p50_ms", "paddle_serving_batch_size_bucket",
          "paddle_serving_queue_latency_ms_bucket",
          "paddle_serving_padding_waste_ratio"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing metrics: {missing}"


def value(name):
    line = [l for l in text.splitlines() if l.startswith(name + " ")][0]
    return float(line.split()[1])


qps, p99 = value("paddle_serving_qps"), value("paddle_serving_p99_ms")
assert qps > 0, f"qps not positive: {qps}"
assert p99 > 0, f"p99 not positive: {p99}"
compiles = value("paddle_serving_compile_count")
print(f"metrics OK: qps={qps:g} p99_ms={p99:g} bucket_compiles={compiles:g}")
EOF

echo "[serve_smoke] SIGTERM -> graceful drain..."
kill -TERM "$SERVER_PID"
rc=0
wait "$SERVER_PID" || rc=$?
SERVER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] server exit code $rc (want 0 = clean drain)"
    cat "$WORK/server.log"
    exit 1
fi
grep -q "serving drain clean" "$WORK/server.log" \
    || { echo "no clean-drain marker in server log"; cat "$WORK/server.log"; exit 1; }
echo "[serve_smoke] clean drain OK"

# ---- concurrent-decode section: continuous-batching generation --------
echo "[serve_smoke] starting generation server..."
python -m paddle_tpu.serving.generation --port 0 --slots 4 \
    --prompt-buckets 8,16 --max-seq-len 48 > "$WORK/genserver.log" 2>&1 &
SERVER_PID=$!

GURL=""
for _ in $(seq 1 600); do
    GURL=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' \
           "$WORK/genserver.log" | head -1)
    [ -n "$GURL" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null \
        || { echo "generation server died:"; cat "$WORK/genserver.log"; exit 1; }
    sleep 0.1
done
[ -n "$GURL" ] || { echo "generation server never came up"; \
    cat "$WORK/genserver.log"; exit 1; }
echo "[serve_smoke] generation server up at $GURL"

echo "[serve_smoke] firing concurrent streaming decode load..."
python -m paddle_tpu.serving.client --url "$GURL" --mode generate \
    --requests 12 --concurrency 6 --prompt-len 8 --max-new 16 \
    --vocab 200 --sample

echo "[serve_smoke] scraping genserve /metrics..."
COMPILES_1=$(python - "$GURL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_genserve_decode_tokens_per_sec",
          "paddle_genserve_ttft_p50_ms", "paddle_genserve_ttft_p99_ms",
          "paddle_genserve_inter_token_p50_ms",
          "paddle_genserve_inter_token_p99_ms",
          "paddle_genserve_slot_occupancy",
          "paddle_genserve_tokens_total",
          "paddle_genserve_compile_count"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing metrics: {missing}"


def value(name):
    line = [l for l in text.splitlines() if l.startswith(name + " ")][0]
    return float(line.split()[1])


tps = value("paddle_genserve_decode_tokens_per_sec")
it_p99 = value("paddle_genserve_inter_token_p99_ms")
ttft = value("paddle_genserve_ttft_p99_ms")
compiles = value("paddle_genserve_compile_count")
assert tps > 0, f"decode tokens/s not positive: {tps}"
assert 0 < it_p99 < 60_000, f"inter-token p99 insane: {it_p99}"
assert ttft > 0, f"ttft p99 not positive: {ttft}"
print(f"genserve metrics OK: tokens/s={tps:g} inter_token_p99_ms="
      f"{it_p99:g} ttft_p99_ms={ttft:g} compiles={compiles:g}",
      file=sys.stderr)
print(int(compiles))
EOF
)

echo "[serve_smoke] second burst (recompile check)..."
python -m paddle_tpu.serving.client --url "$GURL" --mode generate \
    --requests 8 --concurrency 4 --prompt-len 12 --max-new 10 \
    --vocab 200

COMPILES_2=$(python - "$GURL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
line = [l for l in text.splitlines()
        if l.startswith("paddle_genserve_compile_count ")][0]
print(int(float(line.split()[1])))
EOF
)
if [ "$COMPILES_1" != "$COMPILES_2" ]; then
    echo "[serve_smoke] RECOMPILE after warmup: $COMPILES_1 -> $COMPILES_2"
    exit 1
fi
echo "[serve_smoke] zero recompiles after warmup OK ($COMPILES_2 total)"

echo "[serve_smoke] SIGTERM -> generation graceful drain..."
kill -TERM "$SERVER_PID"
rc=0
wait "$SERVER_PID" || rc=$?
SERVER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] generation server exit code $rc (want 0)"
    cat "$WORK/genserver.log"
    exit 1
fi
grep -q "serving drain clean" "$WORK/genserver.log" \
    || { echo "no clean-drain marker in generation server log"; \
         cat "$WORK/genserver.log"; exit 1; }
echo "[serve_smoke] generation clean drain OK"

# ---- paged KV + prefix-cache section ----------------------------------
# an oversubscribed page pool (40 pages < 4 slots * 12 pages/slot) and
# the prefix cache on: every client prompt opens with the SAME 8-token
# system prefix (2 full 4-token pages), so after the first admission
# every admission is a prefix hit
echo "[serve_smoke] starting paged generation server (prefix cache on)..."
python -m paddle_tpu.serving.generation --port 0 --slots 4 \
    --prompt-buckets 8,16 --max-seq-len 48 --page-size 4 --num-pages 40 \
    --prefix-cache 1 > "$WORK/pagedserver.log" 2>&1 &
SERVER_PID=$!

PURL=""
for _ in $(seq 1 600); do
    PURL=$(sed -n 's/.*listening on \(http[^ ]*\).*/\1/p' \
           "$WORK/pagedserver.log" | head -1)
    [ -n "$PURL" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null \
        || { echo "paged server died:"; cat "$WORK/pagedserver.log"; exit 1; }
    sleep 0.1
done
[ -n "$PURL" ] || { echo "paged server never came up"; \
    cat "$WORK/pagedserver.log"; exit 1; }
echo "[serve_smoke] paged server up at $PURL"

echo "[serve_smoke] firing shared-system-prompt load..."
python -m paddle_tpu.serving.client --url "$PURL" --mode generate \
    --requests 12 --concurrency 6 --prompt-len 12 --shared-prefix-len 8 \
    --max-new 12 --vocab 200 --sample

echo "[serve_smoke] scraping paged /metrics..."
python - "$PURL" <<'EOF'
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_genserve_prefix_cache_hits_total",
          "paddle_genserve_prefix_cache_misses_total",
          "paddle_genserve_prefix_cache_hit_ratio",
          "paddle_genserve_page_occupancy",
          "paddle_genserve_ttft_p99_ms"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing metrics: {missing}"


def value(name):
    line = [l for l in text.splitlines() if l.startswith(name + " ")][0]
    return float(line.split()[1])


ratio = value("paddle_genserve_prefix_cache_hit_ratio")
hits = value("paddle_genserve_prefix_cache_hits_total")
ttft = value("paddle_genserve_ttft_p99_ms")
assert ratio > 0, f"prefix hit ratio not positive under shared load: {ratio}"
assert hits > 0, f"no prefix hits under shared-prefix load: {hits}"
assert ttft > 0, f"ttft p99 not positive: {ttft}"
print(f"paged metrics OK: prefix_hit_ratio={ratio:g} hits={hits:g} "
      f"ttft_p99_ms={ttft:g}")
EOF

echo "[serve_smoke] SIGTERM -> paged graceful drain..."
kill -TERM "$SERVER_PID"
rc=0
wait "$SERVER_PID" || rc=$?
SERVER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] paged server exit code $rc (want 0)"
    cat "$WORK/pagedserver.log"
    exit 1
fi
grep -q "serving drain clean" "$WORK/pagedserver.log" \
    || { echo "no clean-drain marker in paged server log"; \
         cat "$WORK/pagedserver.log"; exit 1; }
echo "[serve_smoke] paged clean drain OK"

# ---- fleet router section ---------------------------------------------
# two SPECULATIVE replicas (1-layer derived draft, K=3) behind the
# prefix-aware router: a shared-prefix burst must ride the affinity
# table onto ONE replica (routed prefix_hit ratio at least as good as a
# single replica's own cache ratio), then the router drains clean
# before its replicas do
echo "[serve_smoke] starting 2 replica generation servers..."
python -m paddle_tpu.serving.generation --port 0 --slots 2 \
    --prompt-buckets 8,16 --max-seq-len 48 --page-size 4 --num-pages 40 \
    --prefix-cache 1 --draft-layers 1 --spec-tokens 3 \
    > "$WORK/replica0.log" 2>&1 &
R0_PID=$!
python -m paddle_tpu.serving.generation --port 0 --slots 2 \
    --prompt-buckets 8,16 --max-seq-len 48 --page-size 4 --num-pages 40 \
    --prefix-cache 1 --draft-layers 1 --spec-tokens 3 \
    > "$WORK/replica1.log" 2>&1 &
R1_PID=$!
R0_URL=$(wait_url "$WORK/replica0.log" "$R0_PID") \
    || { echo "replica0 never came up"; cat "$WORK/replica0.log"; exit 1; }
R1_URL=$(wait_url "$WORK/replica1.log" "$R1_PID") \
    || { echo "replica1 never came up"; cat "$WORK/replica1.log"; exit 1; }
echo "[serve_smoke] replicas up at $R0_URL $R1_URL"

echo "[serve_smoke] starting fleet router..."
python -m paddle_tpu.serving.router --replicas "$R0_URL,$R1_URL" \
    --port 0 --page-size 4 --probe-interval 0.2 \
    > "$WORK/router.log" 2>&1 &
ROUTER_PID=$!
RURL=$(wait_url "$WORK/router.log" "$ROUTER_PID") \
    || { echo "router never came up"; cat "$WORK/router.log"; exit 1; }
echo "[serve_smoke] router up at $RURL"

echo "[serve_smoke] firing shared-prefix burst through the router..."
python -m paddle_tpu.serving.client --url "$RURL" --mode generate \
    --requests 12 --concurrency 4 --prompt-len 12 --shared-prefix-len 8 \
    --max-new 10 --vocab 200

echo "[serve_smoke] scraping router /metrics (federated)..."
python - "$RURL" <<'EOF'
import re
import sys
import urllib.request

text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                              timeout=10).read().decode()
needed = ["paddle_router_requests_total", "paddle_router_replicas_healthy",
          "# replica=r0", "# replica=r1",
          "paddle_genserve_spec_accept_ratio"]
missing = [n for n in needed if n not in text]
assert not missing, f"missing from federated metrics: {missing}"


def value(name, section):
    line = [l for l in section.splitlines()
            if l.startswith(name + " ")][0]
    return float(line.split()[1])


healthy = value("paddle_router_replicas_healthy",
                text.split("# replica=")[0])
assert healthy == 2, f"want 2 healthy replicas, got {healthy}"

routed = {}  # (replica, reason) -> count
for m in re.finditer(r'paddle_router_requests_total\{replica="([^"]+)",'
                     r'reason="([^"]+)"\} (\d+)', text):
    routed[(m.group(1), m.group(2))] = int(m.group(3))
total = sum(routed.values())
hit_owners = {r for (r, reason) in routed if reason == "prefix_hit"}
hits = sum(n for (r, reason), n in routed.items()
           if reason == "prefix_hit")
assert total == 12, f"want 12 routed requests, got {total}: {routed}"
assert len(hit_owners) == 1, \
    f"shared prefix must bind ONE replica, got {hit_owners}: {routed}"
assert hits >= 8, f"too few prefix_hit routes: {routed}"

# routed hit-ratio must be at least the owning replica's own cache
# ratio: affinity loses nothing vs pinning every request to one box
owner = hit_owners.pop()
section = [s for s in text.split("# replica=") if s.startswith(owner)][0]
replica_ratio = value("paddle_genserve_prefix_cache_hit_ratio", section)
router_ratio = hits / total
assert router_ratio + 1e-3 >= replica_ratio, \
    f"router hit-ratio {router_ratio} < replica's own {replica_ratio}"
print(f"router metrics OK: routed={routed} router_hit_ratio="
      f"{router_ratio:.3f} {owner}_cache_ratio={replica_ratio:g}")
EOF

echo "[serve_smoke] SIGTERM -> router drain, then replicas..."
kill -TERM "$ROUTER_PID"
rc=0
wait "$ROUTER_PID" || rc=$?
ROUTER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] router exit code $rc (want 0 = clean drain)"
    cat "$WORK/router.log"
    exit 1
fi
grep -q "router drain clean" "$WORK/router.log" \
    || { echo "no clean-drain marker in router log"; \
         cat "$WORK/router.log"; exit 1; }
for pid_var in R0_PID R1_PID; do
    pid=${!pid_var}
    kill -TERM "$pid"
    rc=0
    wait "$pid" || rc=$?
    eval "$pid_var=''"
    [ "$rc" -eq 0 ] || { echo "replica $pid_var exit code $rc (want 0)"; \
                         exit 1; }
done
grep -q "serving drain clean" "$WORK/replica0.log" \
    || { echo "no clean-drain marker in replica0 log"; exit 1; }
grep -q "serving drain clean" "$WORK/replica1.log" \
    || { echo "no clean-drain marker in replica1 log"; exit 1; }
echo "[serve_smoke] router + replica clean drain OK"

# ---- fleet chaos section ----------------------------------------------
# the supervised fleet loses a replica under concurrent streaming load:
# a REAL SIGKILL lands on the affinity-owner replica mid-stream.  The
# router must resume every interrupted stream on the survivor (greedy
# output bitwise-identical to an uninterrupted oracle), report ZERO
# failed requests, measure a failover recovery faster than the
# probe-timeout floor (epoch-delta eviction), and the supervisor must
# respawn the corpse back into a 2-healthy fleet without a restart.
echo "[serve_smoke] starting supervised fleet (world=2)..."
python -m paddle_tpu.serving.fleet --world 2 --heartbeat-timeout 10 \
    --backoff 0.2 --telemetry-dir "$WORK/telemetry" \
    --log-dir "$WORK/fleetlogs" -- \
    python -m paddle_tpu.serving.generation --port 0 --slots 6 \
    --prompt-buckets 8,16,32 --max-seq-len 48 --page-size 4 --seed 0 \
    > "$WORK/fleet.log" 2>&1 &
SUP_PID=$!

for _ in $(seq 1 1800); do
    grep -q "supervising 2 replicas" "$WORK/fleet.log" && break
    kill -0 "$SUP_PID" 2>/dev/null \
        || { echo "fleet supervisor died:"; cat "$WORK/fleet.log"; exit 1; }
    sleep 0.1
done
grep -q "supervising 2 replicas" "$WORK/fleet.log" \
    || { echo "fleet never became ready"; cat "$WORK/fleet.log"; exit 1; }
COORD=$(sed -n 's/^paddle_tpu\.serving\.fleet coord \(.*\)$/\1/p' \
        "$WORK/fleet.log" | head -1)
[ -n "$COORD" ] || { echo "no coord address in fleet log"; \
    cat "$WORK/fleet.log"; exit 1; }
echo "[serve_smoke] fleet up, coordinator at $COORD"

echo "[serve_smoke] starting router on coordinator membership..."
python -m paddle_tpu.serving.router --coord "$COORD" --port 0 \
    --page-size 4 --probe-interval 0.5 --dead-after 3 \
    > "$WORK/chaosrouter.log" 2>&1 &
ROUTER_PID=$!
CRURL=$(wait_url "$WORK/chaosrouter.log" "$ROUTER_PID") \
    || { echo "chaos router never came up"; cat "$WORK/chaosrouter.log"; \
         exit 1; }
echo "[serve_smoke] chaos router up at $CRURL"

echo "[serve_smoke] mid-stream SIGKILL drill (4 streams)..."
python - "$CRURL" "$SUP_PID" <<'EOF'
import os
import signal
import sys
import threading
import urllib.request

from paddle_tpu.serving.client import ServingClient

RURL, SUP = sys.argv[1], int(sys.argv[2])
PROMPT = [3, 5, 7, 11, 13, 17, 19, 23]
MAX_NEW = 24
STREAMS = 4
# probe floor: the recovery the router must BEAT (dead_after * interval)
PROBE_FLOOR_MS = 3 * 0.5 * 1000.0


def children(pid):
    out = []
    task = "/proc/%d/task" % pid
    for t in os.listdir(task):
        with open("%s/%s/children" % (task, t)) as f:
            out += [int(c) for c in f.read().split()]
    return out


def rank_of(pid):
    with open("/proc/%d/environ" % pid, "rb") as f:
        for kv in f.read().split(b"\0"):
            if kv.startswith(b"PADDLE_POD_RANK="):
                return int(kv.split(b"=", 1)[1])
    return None


replica_pid = {rank_of(p): p for p in children(SUP)}
assert {0, 1} <= set(replica_pid), "fleet ranks not found: %r" % replica_pid

# oracle binds the shared-prompt affinity to ONE replica (least-loaded
# tie broken by name -> rank 0); every stream below rides that binding,
# so SIGKILLing rank 0 interrupts them all mid-decode
cli = ServingClient(RURL, timeout=180.0)
oracle = cli.generate(PROMPT, MAX_NEW)["tokens"]
assert len(oracle) == MAX_NEW, oracle

three_tokens = threading.Event()
results = [None] * STREAMS
errors = [None] * STREAMS


def run(i):
    toks, done = [], None
    try:
        for evt in ServingClient(RURL, timeout=180.0).generate_stream(
                PROMPT, MAX_NEW):
            if "token" in evt:
                toks.append(evt["token"])
                if len(toks) >= 3:
                    three_tokens.set()
            if evt.get("done"):
                done = evt
        results[i] = (toks, done)
    except Exception as e:  # noqa: BLE001 - any exception = failed request
        errors[i] = e


threads = [threading.Thread(target=run, args=(i,)) for i in range(STREAMS)]
for t in threads:
    t.start()
assert three_tokens.wait(180), "no stream reached 3 tokens"
os.kill(replica_pid[0], signal.SIGKILL)
print("[chaos] SIGKILLed rank-0 replica pid %d mid-stream"
      % replica_pid[0], file=sys.stderr)
for t in threads:
    t.join(300)
assert not any(t.is_alive() for t in threads), "stream hung after kill"
assert all(e is None for e in errors), \
    "client-visible failures: %r" % [e for e in errors if e]
for toks, done in results:
    assert done is not None and not done.get("error"), done
    assert toks == oracle, \
        "resumed stream diverged:\n got  %r\n want %r" % (toks, oracle)

text = urllib.request.urlopen(RURL + "/metrics",
                              timeout=10).read().decode()
head = text.split("# replica=")[0]


def value(name):
    line = [l for l in head.splitlines() if l.startswith(name + " ")]
    assert line, "missing metric %s" % name
    return float(line[0].split()[-1])


failovers = value('paddle_router_failovers_total{reason="mid_stream"}')
avail = value("paddle_fleet_availability_ratio")
recovery = value("paddle_router_failover_recovery_ms")
assert failovers >= 1, "no mid-stream failover recorded: %g" % failovers
assert avail == 1.0, "availability below 1.0 after drill: %g" % avail
assert 0 < recovery < PROBE_FLOOR_MS, \
    "failover recovery %.1fms must beat the %.0fms probe floor" \
    % (recovery, PROBE_FLOOR_MS)
print("[chaos] drill OK: %d streams resumed bitwise, failovers=%g "
      "availability=%g recovery_ms=%g (probe floor %.0fms)"
      % (STREAMS, failovers, avail, recovery, PROBE_FLOOR_MS))
EOF

echo "[serve_smoke] waiting for supervisor respawn..."
for _ in $(seq 1 1800); do
    grep -q "replica 0 respawned at" "$WORK/fleet.log" && break
    kill -0 "$SUP_PID" 2>/dev/null \
        || { echo "fleet supervisor died:"; cat "$WORK/fleet.log"; exit 1; }
    sleep 0.1
done
grep -q "replica 0 respawned at" "$WORK/fleet.log" \
    || { echo "supervisor never respawned the killed replica"; \
         cat "$WORK/fleet.log"; exit 1; }

python - "$CRURL" <<'EOF'
# membership re-admission: the router must see the respawned replica
# (new url, same rank) and return to 2 healthy WITHOUT a restart
import sys
import time
import urllib.request

deadline = time.time() + 120
while time.time() < deadline:
    text = urllib.request.urlopen(sys.argv[1] + "/metrics",
                                  timeout=10).read().decode()
    line = [l for l in text.splitlines()
            if l.startswith("paddle_router_replicas_healthy ")]
    if line and float(line[0].split()[1]) == 2:
        print("[chaos] respawned replica re-admitted: 2 healthy again")
        sys.exit(0)
    time.sleep(0.25)
sys.exit("router never re-admitted the respawned replica")
EOF

echo "[serve_smoke] SIGTERM -> chaos router drain, then fleet..."
kill -TERM "$ROUTER_PID"
rc=0
wait "$ROUTER_PID" || rc=$?
ROUTER_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] chaos router exit code $rc (want 0)"
    cat "$WORK/chaosrouter.log"
    exit 1
fi
grep -q "router drain clean" "$WORK/chaosrouter.log" \
    || { echo "no clean-drain marker in chaos router log"; \
         cat "$WORK/chaosrouter.log"; exit 1; }
kill -TERM "$SUP_PID"
rc=0
wait "$SUP_PID" || rc=$?
SUP_PID=""
if [ "$rc" -ne 0 ]; then
    echo "[serve_smoke] fleet supervisor exit code $rc (want 0)"
    cat "$WORK/fleet.log"
    exit 1
fi
grep -q "fleet drain clean" "$WORK/fleet.log" \
    || { echo "no clean-drain marker in fleet log"; \
         cat "$WORK/fleet.log"; exit 1; }
echo "[serve_smoke] fleet chaos drill OK"

exec python -m pytest tests/ -q \
    -m "serving or genserve or specdec or fleetchaos" \
    -p no:cacheprovider -p no:randomly "$@"
