#!/usr/bin/env bash
# Sparse/recommender smoke: proves the paddle_tpu.sparse plane end to
# end on a dp2×fsdp2×tp2 mesh of 8 virtual CPU devices.
#
# Runs the wide-and-deep example (examples/wide_deep_fleet.py) and
# asserts
#   * the streaming click-log fit LEARNS (tail loss < head loss) with
#     vocab admission running on the prefetch thread,
#   * the item table is genuinely row-sharded — the buffer census's
#     largest per-device shard is strictly smaller than the full table
#     bytes (the "table larger than one device's share" claim),
#   * the AOT-warmed serving engine answers a pooled-lookup burst with
#     ZERO steady-state compiles and a bounded p99,
# then runs the sparse-marked pytest suite (numerics parity vs the
# one-hot oracle, admission/eviction determinism, elastic checkpoint
# round-trip of table+vocab across a mesh-geometry change, streaming
# reproducibility).  Extra args pass to pytest.
set -euo pipefail
cd "$(dirname "$0")/.."

# static-analysis preflight (tools/lint.sh): fail fast on PTA violations
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh || { echo "$(basename "$0"): lint preflight failed"; exit 1; }
fi

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

# the example asserts loss decrease, shard<full census bytes, and the
# zero-steady-state-compile serving burst; rc!=0 on any violation
python examples/wide_deep_fleet.py
echo "[sparse_smoke] wide_deep_fleet OK (sharded fit + serving burst)"

# serving tail-latency tripwire: a warmed engine must answer a burst
# with a sane p99 (generous bound — virtual devices share host cores)
python - <<'EOF'
import numpy as np

import paddle_tpu.sparse as sparse
from paddle_tpu.distributed.mesh import build_mesh

rs = np.random.RandomState(0)
table = rs.randn(4096, 32).astype(np.float32)
mesh = build_mesh({"dp": 2, "fsdp": 2, "tp": 2})
eng = sparse.lookup_engine(table, mesh=mesh, max_batch_size=8,
                           id_buckets=(2, 4, 8))
with eng:
    c0 = eng.metrics.snapshot()["compile_count"]
    for _ in range(200):
        eng.predict([rs.randint(0, 4096, size=rs.randint(1, 9))])
    s = eng.metrics.snapshot()
assert s["compile_count"] == c0, "steady-state serving compiled!"
assert s["p99_ms"] < 500.0, f"lookup p99 {s['p99_ms']}ms out of bounds"
print(f"[sparse_smoke] serving burst: {s['responses']} lookups, "
      f"p50 {s['p50_ms']}ms p99 {s['p99_ms']}ms, 0 steady-state compiles")
EOF

exec python -m pytest tests/ -q -m sparse \
    -p no:cacheprovider -p no:randomly "$@"
