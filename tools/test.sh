#!/bin/bash
# CPU-only test runner. Strips the axon pool IP BEFORE python starts so the
# environment's sitecustomize never registers/dials the single-client TPU
# tunnel (register() runs at interpreter startup and blocks when the tunnel
# is held or wedged — see bench.py _tunnel_lock). Always run the test suite
# through this wrapper while any TPU bench is running.
cd /root/repo || exit 1
if [ $# -eq 0 ]; then set -- tests/ -q; fi
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python -m pytest "$@"
