#!/bin/bash
# CPU-only test runner. Strips the axon pool IP BEFORE python starts so the
# environment's sitecustomize never registers/dials the single-client TPU
# tunnel (register() runs at interpreter startup and blocks when the tunnel
# is held or wedged — see bench.py _tunnel_lock). Always run the test suite
# through this wrapper while any TPU bench is running.
cd /root/repo || exit 1
# static-analysis preflight: a PTA violation fails the run before pytest
# starts (skip with PADDLE_SKIP_LINT=1 when iterating on a known-dirty tree)
if [ "${PADDLE_SKIP_LINT:-0}" != "1" ]; then
    tools/lint.sh > /tmp/paddle_lint.$$ 2>&1 || {
        cat /tmp/paddle_lint.$$; rm -f /tmp/paddle_lint.$$
        echo "tools/test.sh: static analysis failed (tools/lint.sh)"; exit 1
    }
    rm -f /tmp/paddle_lint.$$
fi
if [ $# -eq 0 ]; then set -- tests/ -q; fi
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python -m pytest "$@"
