#!/usr/bin/env python
"""Interactive on-chip A/B harness: run one bench config under variant
environments and print a comparison table.  For the perf-tuning session
when the TPU tunnel is up (BASELINE.md headline configs) — e.g. is the
Pallas flash-attention kernel actually faster than plain-XLA attention
at BERT's seq 128, and does the space-to-depth stem pay off at 224^2?

Usage:  python tools/tpu_ab.py bert
        python tools/tpu_ab.py resnet50
"""
import json
import os
import subprocess
import sys

VARIANTS = {
    "bert": [
        ("pallas_flash", {"FLAGS_USE_PALLAS_KERNELS": "1"}),
        ("xla_attention", {"FLAGS_USE_PALLAS_KERNELS": "0"}),
    ],
    "ernie": [
        ("pallas_flash", {"FLAGS_USE_PALLAS_KERNELS": "1"}),
        ("xla_attention", {"FLAGS_USE_PALLAS_KERNELS": "0"}),
    ],
    "resnet50": [
        ("default", {}),
    ],
    "longseq": [
        ("pallas_flash", {"FLAGS_USE_PALLAS_KERNELS": "1"}),
    ],
}


def run(cfg, name, extra_env, timeout=1500):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env)
    p = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"), "--config", cfg],
        env=env, capture_output=True, text=True, timeout=timeout)
    for line in reversed(p.stdout.splitlines()):
        if line.startswith("{") and '"metric"' in line:
            d = json.loads(line)
            if not d.get("partial"):
                return d
    return {"error": p.stderr[-300:]}


def main():
    cfg = sys.argv[1] if len(sys.argv) > 1 else "bert"
    rows = []
    for name, env in VARIANTS.get(cfg, [("default", {})]):
        print(f"[ab] running {cfg} variant {name} ...", file=sys.stderr)
        r = run(cfg, name, env)
        rows.append((name, r))
        print(json.dumps({"variant": name, **r}), flush=True)
    best = max((r for _, r in rows if "value" in r),
               key=lambda r: r.get("value", 0), default=None)
    if best:
        print(json.dumps({"metric": f"{cfg}_ab_best",
                          "value": best.get("value"),
                          "unit": best.get("unit", ""),
                          "vs_baseline": best.get("vs_baseline", 0.0),
                          "winner": [n for n, r in rows if r is best][0]}),
              flush=True)


if __name__ == "__main__":
    main()
