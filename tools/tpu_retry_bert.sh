#!/bin/bash
# Retry loop: capture the BERT headline on TPU when the tunnel recovers.
# (round-3 verdict #1: record TPU evidence whenever the chip is reachable)
cd /root/repo
for i in $(seq 1 60); do
  probe=$(timeout 150 python bench.py --probe 2>/dev/null | tail -1)
  if echo "$probe" | grep -q '"ok": true' && ! echo "$probe" | grep -q '"platform": "cpu"'; then
    echo "$(date -u +%FT%TZ) TPU up, running bert" >> /tmp/bert_tpu_retry.log
    timeout 1800 python bench.py --config bert > /tmp/bert_try.json 2>>/tmp/bert_tpu_retry.log
    if grep -q 'samples_per_sec_per_chip' /tmp/bert_try.json; then
      cp /tmp/bert_try.json /tmp/bert_tpu_line.json
      echo "$(date -u +%FT%TZ) SUCCESS" >> /tmp/bert_tpu_retry.log
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) probe down" >> /tmp/bert_tpu_retry.log
  fi
  sleep 420
done
