#!/bin/bash
# Watch for the axon TPU tunnel to return; when it does, run the full
# bench and append the TPU-platform lines to BENCH_session_r04.jsonl
# (round-3 verdict #1: record TPU evidence whenever the chip is
# reachable — the tunnel has multi-hour transient outages).
cd /root/repo
LOG=/tmp/tpu_watch.log
for i in $(seq 1 60); do
  probe=$(timeout 150 python bench.py --probe 2>/dev/null | tail -1)
  if echo "$probe" | grep -q '"ok": true' && ! echo "$probe" | grep -q '"platform": "cpu"'; then
    echo "$(date -u +%FT%TZ) TPU up; running full bench" >> "$LOG"
    timeout 5400 python bench.py > /tmp/bench_r4_run2.jsonl 2>>"$LOG"
    if grep -q '"platform": "TPU' /tmp/bench_r4_run2.jsonl; then
      ntpu=$(grep -c '"platform": "TPU' /tmp/bench_r4_run2.jsonl)
      bert=$(grep -q 'bert_base_samples_per_sec_per_chip' /tmp/bench_r4_run2.jsonl && echo yes || echo no)
      {
        echo "{\"metric\": \"session_note\", \"value\": 1.0, \"unit\": \"note\", \"vs_baseline\": 0.0, \"note\": \"second session run $(date -u +%FT%TZ) after tunnel recovery; tpu_lines=$ntpu bert_on_tpu=$bert\"}"
        cat /tmp/bench_r4_run2.jsonl
      } >> BENCH_session_r04.jsonl
      git commit -q -m "Record second TPU bench session (tunnel recovery)" -- BENCH_session_r04.jsonl
      echo "$(date -u +%FT%TZ) SUCCESS committed (tpu_lines=$ntpu bert=$bert)" >> "$LOG"
      if [ "$bert" = yes ]; then exit 0; fi
      echo "$(date -u +%FT%TZ) bert still missing; continuing watch" >> "$LOG"
    else
      echo "$(date -u +%FT%TZ) bench ran but no TPU lines; will retry" >> "$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) probe down" >> "$LOG"
  fi
  sleep 420
done
