#!/bin/bash
# Watch for the axon TPU tunnel to return; when it does, run the full
# bench and append the TPU-platform lines to BENCH_session_r04.jsonl
# (round-3 verdict #1: record TPU evidence whenever the chip is
# reachable — the tunnel has multi-hour transient outages).
cd /root/repo
LOG=/tmp/tpu_watch.log
for i in $(seq 1 60); do
  probe=$(timeout 150 python bench.py --probe 2>/dev/null | tail -1)
  if echo "$probe" | grep -q '"ok": true' && ! echo "$probe" | grep -q '"platform": "cpu"'; then
    echo "$(date -u +%FT%TZ) TPU up; running full bench" >> "$LOG"
    timeout 5400 python bench.py > /tmp/bench_r4_run2.jsonl 2>>"$LOG"
    if grep -q '"platform": "TPU' /tmp/bench_r4_run2.jsonl; then
      {
        echo "{\"metric\": \"session_note\", \"value\": 1.0, \"unit\": \"note\", \"vs_baseline\": 0.0, \"note\": \"second session run $(date -u +%FT%TZ) after tunnel recovery; includes s2d-stem/batch-128 resnet and the bert headline\"}"
        cat /tmp/bench_r4_run2.jsonl
      } >> BENCH_session_r04.jsonl
      git add BENCH_session_r04.jsonl
      git commit -q -m "Record second TPU bench session (tunnel recovery): bert headline + s2d-stem resnet numbers"
      echo "$(date -u +%FT%TZ) SUCCESS committed" >> "$LOG"
      exit 0
    fi
    echo "$(date -u +%FT%TZ) bench ran but no TPU lines; will retry" >> "$LOG"
  else
    echo "$(date -u +%FT%TZ) probe down" >> "$LOG"
  fi
  sleep 420
done
