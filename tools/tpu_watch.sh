#!/bin/bash
# Watch for the axon TPU tunnel to return; when it does, run the full
# bench and append the TPU-platform lines to BENCH_session_r05.jsonl.
# (VERDICT r04 next-step #1: TPU evidence whenever the chip is
# reachable — the tunnel has multi-hour transient outages.)
cd /root/repo
LOG=/tmp/tpu_watch.log
RUN=/tmp/bench_r5_watch.jsonl
# Cadence: 15 min between probes. The tunnel relay is single-client and a
# failed dial may extend the wedge; sparse probes give the grant time to
# expire. The probe is wrapped in a NON-BLOCKING flock on the shared
# tunnel lock taken BEFORE python starts (the sitecustomize register()
# dials at interpreter startup): if another process holds the tunnel the
# cycle is skipped, never contended. The full-bench run is NOT wrapped —
# bench.py's drive() takes the same lock around each subprocess itself
# (an outer hold here would deadlock those).
for i in $(seq 1 30); do
  probe=$(flock -n /tmp/axon_tunnel.lock -c "timeout 250 python bench.py --probe" 2>/dev/null | tail -1)
  if [ -z "$probe" ]; then
    echo "$(date -u +%FT%TZ) lock busy or probe hung; skipping cycle" >> "$LOG"
    sleep 900; continue
  fi
  if echo "$probe" | grep -q '"ok": true' && ! echo "$probe" | grep -q '"platform": "cpu"'; then
    echo "$(date -u +%FT%TZ) TPU up; running full bench" >> "$LOG"
    timeout 9000 python bench.py > "$RUN" 2>>"$LOG"
    if grep -q '"platform": "TPU' "$RUN"; then
      ntpu=$(grep -c '"platform": "TPU' "$RUN")
      bert=$(grep -q 'bert_base_samples_per_sec_per_chip' "$RUN" && echo yes || echo no)
      {
        echo "{\"metric\": \"session_note\", \"value\": 1.0, \"unit\": \"note\", \"vs_baseline\": 0.0, \"note\": \"r05 watch run $(date -u +%FT%TZ); tpu_lines=$ntpu bert_on_tpu=$bert\"}"
        cat "$RUN"
      } >> BENCH_session_r05.jsonl
      git add BENCH_session_r05.jsonl
      git commit -q -m "Record TPU bench session (r05 watcher)" -- BENCH_session_r05.jsonl
      echo "$(date -u +%FT%TZ) SUCCESS committed (tpu_lines=$ntpu bert=$bert)" >> "$LOG"
      if [ "$bert" = yes ]; then exit 0; fi
      echo "$(date -u +%FT%TZ) bert still missing; continuing watch" >> "$LOG"
    else
      echo "$(date -u +%FT%TZ) bench ran but no TPU lines; will retry" >> "$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) probe down" >> "$LOG"
  fi
  sleep 900
done
